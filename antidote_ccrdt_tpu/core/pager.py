"""Out-of-core partitions: the hot/cold partition pager.

At the ROADMAP's million-user scale the id space of one topk /
leaderboard / wordcount instance dwarfs the HBM budget; before this
module every partition of every instance was device-resident or
nothing. Big(ger) Sets (arxiv 1605.06424, PAPERS.md) solved the same
whole-state-round-trip problem in Riak by decomposing state so
operations touch only fragments — and with the mesh plane landed, the
paging unit is already in hand: the SHARD-LOCAL partition
(`core/partition.py`), serialized as a CCPT blob (the transfer format
IS the storage format) and billed by the serve plane's per-key access
stats.

Residency model
---------------
A `PartitionPager` splits one instance's partitions (only
`MeshPlan.owned_parts` under the mesh — each chip pages its own
partitions independently) into two tiers:

* **hot** — resident in the device state exactly as before. Ops,
  merges, and serves against hot partitions run at device speed with
  zero pager involvement.
* **cold** — demoted out of the device state: the partition's id-slices
  are reset to the engine's join identity (``dense.init`` values) and
  the content lives host-side twice over — as the serialized psnap
  payload (the CCPT storage/transfer blob: RAM dict, spilling to disk
  past ``CCRDT_PAGER_HOST_BUDGET``) and joined into a CPU-backed
  "cold substrate" state used for host folds and digest recomputation.

The invariant the whole design hangs on: **logical state = device
state ⊔ cold substrate**, with the two disjoint along the item axis
(device is identity on cold slices, the substrate is identity on hot
slices and the meta leaves). Join semantics make the decomposition
exact — `full_state` reassembles the logical state bit-identically,
which the working-set drill pins against an all-resident reference.

The meta partition P (vc / lossy / whole leaves) is pinned resident and
never demoted. Lifted monoid states are not pageable (they partition by
replica row, not id) and bare MONOID engines are rejected for the same
reason `restrict_psnap` rejects them (re-merge double-counts).

Traffic that misses
-------------------
* **Ops / serves** call `ensure_resident` first: cold partitions
  hydrate on demand (decode the stored CCPT payload, one device join),
  billing `pager.hydrations` + a `pager.miss_ms` histogram sample and
  firing the `pager.hydrate` fault point.
* **Gossip / anti-entropy never block on a page-in**: a peer delta
  touching cold partitions is SPLIT (`partition.split_delta`) — the hot
  half joins on device, the cold half folds host-side through the same
  jitted merge slots compiled for CPU (`batch_merge.host_merge_into`),
  or, with ``CCRDT_PAGER_FOLD=0``, queues until hydration.
* **Digest / psnap requests** for cold partitions answer straight from
  the pager: cached crc entries and the stored CCPT payload — no
  hydration, no device work.

Promotion/demotion policy: clock (second-chance LRU) over the owned
partitions, fed by `note_ids` (the serve plane's per-key access
stream) and `touch` (op-path partition counters), bounded by
``CCRDT_PAGER_HBM_BUDGET`` bytes of resident item slices.
``CCRDT_PAGER=0`` is the kill-switch: `maybe_pager` returns None and
every integration point (all take ``pager=None``) stays the
bit-identical all-resident legacy path.

Crash safety: spill files are strictly a cache of durable-elsewhere
content (WAL + checkpoints recover the logical state all-resident), so
a recovering process DISCARDS any spill left by a torn predecessor —
`discard_spill`, called from WAL recovery — rather than trusting a
blob that may be mid-write.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import partition as pt
from . import serial
from .batch_merge import host_device, host_merge_into, merge_into
from ..obs import devprof, profile
from ..obs import spans as obs_spans
from ..utils import faults
from ..utils.metrics import Metrics

ENV_FLAG = "CCRDT_PAGER"  # "0"/"false"/"off" => kill-switch (all-resident)
ENV_HBM = "CCRDT_PAGER_HBM_BUDGET"  # bytes of resident item slices (0 = unbounded)
ENV_HOST = "CCRDT_PAGER_HOST_BUDGET"  # bytes of RAM-tier payloads before disk spill
ENV_FOLD = "CCRDT_PAGER_FOLD"  # "0" => queue cold deltas until hydration

# Conditional span, deliberately NOT in spans.PHASES (same contract as
# round.serve_swap): it only lights when a partition actually hydrates.
SPAN_HYDRATE = "round.pager_hydrate"

SPILL_PREFIX = "pagercold-"
_REF_CAP = 8  # clock counter ceiling: bounds the second chances a hot streak buys


def enabled(default: bool = True) -> bool:
    """The ``CCRDT_PAGER`` kill-switch (mirrors CCRDT_OVERLAP/CCRDT_MESH)."""
    v = os.environ.get(ENV_FLAG)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "off", "no", "")


def _env_bytes(name: str, default: int = 0) -> int:
    """Parse a byte-count env knob; bare ints or k/m/g suffixes."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    mult = 1
    if raw[-1] in "kmg":
        mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[raw[-1]]
        raw = raw[:-1]
    try:
        return max(0, int(float(raw) * mult))
    except ValueError:
        return default


def hbm_budget(default: int = 0) -> int:
    return _env_bytes(ENV_HBM, default)


def host_budget(default: int = 0) -> int:
    return _env_bytes(ENV_HOST, default)


def fold_cold_default(default: bool = True) -> bool:
    v = os.environ.get(ENV_FOLD)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "off", "no")


def discard_spill(spill_dir: Optional[str]) -> int:
    """Delete every pager spill file under `spill_dir`. Called on pager
    construction AND from WAL recovery: spill blobs are a cache of
    state that is durable elsewhere, and a file left by a SIGKILLed
    predecessor may be torn mid-write — recovery must rebuild
    all-resident from WAL/checkpoint, never resurrect a spill blob."""
    if not spill_dir or not os.path.isdir(spill_dir):
        return 0
    n = 0
    for fn in os.listdir(spill_dir):
        if fn.startswith(SPILL_PREFIX):
            try:
                os.unlink(os.path.join(spill_dir, fn))
                n += 1
            except OSError:
                pass
    return n


def clear_parts(dense: Any, state: Any, parts: Sequence[int], P: int) -> Any:
    """Reset the id-slices of `parts` to the engine's join identity
    (``dense.init`` values) in every item leaf; whole leaves untouched.
    This is demotion's device-side half: after it, the device state is
    the join identity on those partitions, so joining the cold substrate
    back (`full_state`) reassembles the logical state exactly."""
    import jax
    import jax.numpy as jnp

    want = sorted(int(p) for p in parts if int(p) != P)
    items, _whole, extent = pt._item_plan(state)
    if not want or not extent:
        return state
    sel = np.isin(pt.part_of(np.arange(extent), P), np.asarray(want, np.int64))
    idx = np.nonzero(sel)[0]
    if idx.size == 0:
        return state
    axis_by_id = {id(leaf): axis for _p, leaf, axis in items}
    leaves, treedef = jax.tree_util.tree_flatten(state)
    R, NK = leaves[0].shape[:2]
    ident_leaves = jax.tree_util.tree_flatten(dense.init(R, NK))[0]
    out, matched = [], 0
    for leaf, ileaf in zip(leaves, ident_leaves):
        axis = axis_by_id.get(id(leaf))
        if axis is None:
            out.append(leaf)
            continue
        matched += 1
        arr = np.array(leaf)  # host copy; the scatter below mutates it
        src = np.asarray(ileaf)
        sl: List[Any] = [slice(None)] * arr.ndim
        sl[axis] = idx
        arr[tuple(sl)] = src[tuple(sl)]
        out.append(jnp.asarray(arr))
    if matched != len({id(leaf) for _p, leaf, _a in items}):
        raise RuntimeError("pager clear_parts: item-leaf identity map failed")
    return jax.tree_util.tree_unflatten(treedef, out)


def maybe_pager(
    dense: Any,
    like_state: Any,
    *,
    owned: Optional[Iterable[int]] = None,
    metrics: Optional[Metrics] = None,
    spill_dir: Optional[str] = None,
    P: Optional[int] = None,
    name: Optional[str] = None,
    require_budget: bool = True,
) -> Optional["PartitionPager"]:
    """Env-gated factory: a pager iff ``CCRDT_PAGER`` is not switched
    off, a ``CCRDT_PAGER_HBM_BUDGET`` is configured (unless
    `require_budget=False`), and the engine is pageable — None otherwise,
    which every integration point treats as the all-resident legacy."""
    if not enabled():
        return None
    hbm = hbm_budget()
    if require_budget and not hbm:
        return None
    try:
        return PartitionPager(
            dense,
            like_state,
            P=P,
            name=name,
            owned=owned,
            hbm_budget_bytes=hbm or None,
            host_budget_bytes=host_budget() or None,
            spill_dir=spill_dir,
            metrics=metrics,
        )
    except ValueError:
        return None  # unpageable engine (lifted / bare MONOID)


class PartitionPager:
    """Per-chip hot/cold residency manager for one instance's partitions.

    Thread discipline: same as the state it manages — all mutation from
    the owner's gossip/op loop. The metrics registry is the only member
    other threads read."""

    def __init__(
        self,
        dense: Any,
        like_state: Any,
        *,
        P: Optional[int] = None,
        name: Optional[str] = None,
        owned: Optional[Iterable[int]] = None,
        hbm_budget_bytes: Optional[int] = None,
        host_budget_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
        metrics: Optional[Metrics] = None,
        fold_cold: Optional[bool] = None,
    ) -> None:
        import jax

        from .behaviour import MergeKind

        if pt._is_lifted(like_state):
            raise ValueError(
                "pager does not support lifted monoid states (they "
                "partition by replica row, not id)"
            )
        if getattr(dense, "merge_kind", None) == MergeKind.MONOID:
            raise ValueError(
                "pager does not support bare MONOID engines (their "
                "psnaps are unsound — same restriction as restrict_psnap)"
            )
        self.dense = dense
        self.P = int(P) if P else pt.n_partitions()
        self.name = name or getattr(dense, "type_name", "dense")
        self.metrics = metrics if metrics is not None else Metrics()
        self.fold_cold = fold_cold_default() if fold_cold is None else bool(fold_cold)
        self.spill_dir = spill_dir
        discard_spill(spill_dir)

        items, whole, extent = pt._item_plan(like_state)
        self.extent = int(extent)
        leaves = jax.tree_util.tree_leaves(like_state)
        self._R, self._NK = (int(x) for x in leaves[0].shape[:2])
        per_id = 0
        for _p, leaf, axis in items:
            n_items = max(int(leaf.shape[axis]), 1)
            per_id += int(np.asarray(leaf).nbytes) // n_items
        counts = (
            np.bincount(pt.part_of(np.arange(self.extent), self.P), minlength=self.P)
            if self.extent
            else np.zeros(self.P, np.int64)
        )
        self.part_bytes: Dict[int, int] = {
            p: per_id * int(counts[p]) for p in range(self.P)
        }
        self.meta_bytes = sum(int(np.asarray(l).nbytes) for _p, l in whole)
        universe = sorted(
            int(p)
            for p in (owned if owned is not None else range(self.P))
            if 0 <= int(p) < self.P
        )
        self.universe: List[int] = universe
        self.resident: Set[int] = set(universe)
        self.hbm_budget = int(hbm_budget_bytes) if hbm_budget_bytes else 0
        self.host_budget = int(host_budget_bytes) if host_budget_bytes else 0

        from ..parallel.delta import like_delta_for

        self._like_delta = like_delta_for(dense, like_state)
        self._cold: Optional[Any] = None  # host substrate (identity except cold)
        self._payloads: Dict[int, bytes] = {}  # RAM tier: CCPT psnap payloads
        self._spilled: Dict[int, str] = {}  # disk tier: part -> spill path
        self._digests: Dict[int, int] = {}  # cached crc32 per cold part
        self._queued: List[Tuple[frozenset, Any]] = []  # (cold parts, delta)
        self._ref: Dict[int, int] = {p: 0 for p in universe}
        self._hand = 0
        self.hits = 0
        self.misses = 0
        self._export()

    # --- residency queries -------------------------------------------------

    def is_resident(self, part: int) -> bool:
        return int(part) == self.P or int(part) in self.resident

    def cold_parts(self) -> Set[int]:
        return set(self.universe) - self.resident

    def has_cold(self) -> bool:
        return len(self.resident) < len(self.universe)

    def resident_bytes(self) -> int:
        return self.meta_bytes + sum(self.part_bytes[p] for p in self.resident)

    def host_bytes(self) -> int:
        return sum(len(b) for b in self._payloads.values())

    def parts_for_ids(self, ids: Any) -> List[int]:
        a = np.asarray(ids)
        if a.size == 0:
            return []
        return [int(x) for x in np.unique(pt.part_of(a, self.P))]

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return (self.hits / total) if total else 1.0

    # --- access accounting (policy inputs) ---------------------------------

    def touch(self, parts: Iterable[int], weight: int = 1) -> None:
        """Op-path partition counters: bump clock recency."""
        for p in parts:
            p = int(p)
            if p in self._ref:
                self._ref[p] = min(self._ref[p] + weight, _REF_CAP)

    def note_ids(self, ids: Any) -> None:
        """Serve-plane per-key access stream (the answered row ids)."""
        self.touch(self.parts_for_ids(ids))

    # --- demote / hydrate ---------------------------------------------------

    def demote(self, state: Any, part: int) -> Any:
        """Move one resident partition to the cold tier: serialize its
        psnap (the CCPT payload IS the stored representation), join it
        into the host substrate, reset the device slice to identity."""
        part = int(part)
        if part == self.P or part not in self.resident:
            return state
        psnap = pt.restrict_psnap(self.dense, state, part, self.P)
        payload = serial.dumps_dense(f"{self.name}_psnap", psnap)
        self._digests[part] = pt.digest_entries(state, self.P, [part])[part]
        self._fold_into_cold(psnap)
        state = clear_parts(self.dense, state, [part], self.P)
        self.resident.discard(part)
        self._store_payload(part, payload)
        self.metrics.count("pager.evictions")
        self._export()
        return state

    def hydrate(self, state: Any, part: int) -> Any:
        """Bring one cold partition back device-resident by decoding and
        joining its stored CCPT payload (so every hydration round-trips
        the storage format), then clear it out of the host substrate."""
        part = int(part)
        if part == self.P or part in self.resident:
            return state
        t0 = time.perf_counter()
        tok = (
            obs_spans.begin(SPAN_HYDRATE, part=part) if obs_spans.ACTIVE else None
        )
        try:
            if faults.ACTIVE:
                faults.fire("pager.hydrate")
            payload = self._load_payload(part)
            _name, psnap = serial.loads_dense(payload, self._like_delta)
            if profile.ACTIVE or devprof.ACTIVE:
                # No single jit cache to watch (apply_psnap scatters
                # eagerly), but the dispatch timing + h2d bytes of a
                # hydration are device-observatory evidence.
                with profile.dispatch("pager.hydrate", operands=(psnap,)):
                    state = pt.apply_psnap(self.dense, state, psnap)
            else:
                state = pt.apply_psnap(self.dense, state, psnap)
            if self._cold is not None:
                with host_device():
                    self._cold = clear_parts(self.dense, self._cold, [part], self.P)
            self._drop_payload(part)
            self._digests.pop(part, None)
            self.resident.add(part)
            self.metrics.count("pager.hydrations")
        finally:
            obs_spans.end(tok)
        self.metrics.observe("pager.miss_ms", (time.perf_counter() - t0) * 1e3)
        state = self._drain_queue(state)
        self._export()
        return state

    def ensure_resident(self, state: Any, parts: Iterable[int]) -> Any:
        """The op/serve front door: hydrate whatever of `parts` is cold
        (billing hit/miss), bump recency, and re-enforce the HBM budget
        demoting ONLY partitions outside `parts`."""
        want = [int(p) for p in parts if int(p) != self.P]
        for p in want:
            if p in self.resident or p not in self._ref:
                self.hits += 1
            else:
                self.misses += 1
                state = self.hydrate(state, p)
        self.touch(want)
        return self.enforce_budget(state, protect=want)

    def ensure_resident_ids(self, state: Any, ids: Any) -> Any:
        return self.ensure_resident(state, self.parts_for_ids(ids))

    def enforce_budget(self, state: Any, protect: Iterable[int] = ()) -> Any:
        """Demote clock victims until resident item bytes fit the HBM
        budget. `protect` pins the partitions the caller is about to
        touch. No budget configured ⇒ no-op."""
        if not self.hbm_budget or not self.universe:
            return state
        protected = {int(p) for p in protect}
        # Bounded sweep: every visit either demotes or decays a ref
        # counter, so the clock terminates even when everything is hot.
        fuel = len(self.universe) * (_REF_CAP + 2)
        while self.resident_bytes() > self.hbm_budget and fuel > 0:
            victim = self._clock_victim(protected, fuel)
            if victim is None:
                break
            state = self.demote(state, victim)
            fuel -= 1
        return state

    def _clock_victim(self, protected: Set[int], fuel: int) -> Optional[int]:
        n = len(self.universe)
        for _ in range(min(fuel, n * (_REF_CAP + 2))):
            p = self.universe[self._hand % n]
            self._hand += 1
            if p not in self.resident or p in protected:
                continue
            if self._ref.get(p, 0) > 0:
                self._ref[p] -= 1  # second chance
                continue
            return p
        return None

    # --- the cold substrate -------------------------------------------------

    def _fold_into_cold(self, delta: Any) -> None:
        """Join one delta-shaped payload into the host substrate through
        the CPU-compiled jitted merge slots (core/batch_merge)."""
        from ..parallel import delta as dl

        with host_device():
            if self._cold is None:
                self._cold = self.dense.init(self._R, self._NK)
            if isinstance(delta, dl.TopkRmvDelta):
                expanded = dl.expand_delta(self.dense, delta)
            else:
                expanded = dl.expand_table_delta(self.dense, self._cold, delta)
        self._cold = host_merge_into(
            self.dense.merge, self._cold, expanded, site="pager.cold_fold"
        )

    def _refresh_cold(self, parts: Iterable[int]) -> None:
        """Re-derive payload + digest for cold partitions whose substrate
        content just changed — one leaf walk covers all of them."""
        want = sorted({int(p) for p in parts} & self.cold_parts())
        if not want or self._cold is None:
            return
        digs = pt.digest_entries(self._cold, self.P, want)
        for part in want:
            psnap = pt.restrict_psnap(self.dense, self._cold, part, self.P)
            self._store_payload(
                part, serial.dumps_dense(f"{self.name}_psnap", psnap)
            )
            self._digests[part] = digs[part]

    # --- gossip/anti-entropy integration ------------------------------------

    def apply_delta(self, state: Any, delta: Any) -> Any:
        """Join a peer delta (or decoded psnap) into the logical state
        WITHOUT hydrating: hot half on device, cold half folded into the
        host substrate (or queued under CCRDT_PAGER_FOLD=0)."""
        from ..parallel.delta import apply_any_delta

        cold = self.cold_parts()
        if not cold:
            return apply_any_delta(self.dense, state, delta)
        parts = pt.delta_parts(self.dense, state, delta, self.P)
        hit_cold = parts & cold
        if not hit_cold:
            return apply_any_delta(self.dense, state, delta)
        hot, coldd = pt.split_delta(self.dense, state, delta, self.P, hit_cold)
        if hot is not None:
            state = apply_any_delta(self.dense, state, hot)
        if coldd is not None:
            if self.fold_cold:
                self._fold_into_cold(coldd)
                self._refresh_cold(hit_cold)
                self.metrics.count("pager.cold_folds")
            else:
                self._queued.append((frozenset(hit_cold), coldd))
                self.metrics.count("pager.queued_deltas")
        return state

    def apply_payload(self, state: Any, payload: bytes) -> Any:
        """Anti-entropy repair entry: a fetched psnap payload joins hot
        on device / cold host-side, exactly like a delta."""
        _name, psnap = serial.loads_dense(payload, self._like_delta)
        return self.apply_delta(state, psnap)

    def absorb_peer(self, peer: Any) -> Any:
        """Fold the cold-partition slices of a full peer state into the
        host tier; returns the peer with those slices cleared, safe for
        the caller's ordinary device merge. Full snapshots always fold
        (anchors are rare; queueing a whole state buys nothing)."""
        cold = sorted(self.cold_parts())
        if not cold:
            return peer
        for part in cold:
            self._fold_into_cold(pt.restrict_psnap(self.dense, peer, part, self.P))
        self._refresh_cold(cold)
        self.metrics.count("pager.cold_folds", len(cold))
        return clear_parts(self.dense, peer, cold, self.P)

    def _drain_queue(self, state: Any) -> Any:
        """After a hydration, re-attempt queued deltas: partitions now
        resident apply on device; still-cold remainders re-queue."""
        if not self._queued:
            return state
        from ..parallel.delta import apply_any_delta

        pending, self._queued = self._queued, []
        for parts, delta in pending:
            still_cold = set(parts) & self.cold_parts()
            if not still_cold:
                state = apply_any_delta(self.dense, state, delta)
                self.metrics.count("pager.queue_drains")
                continue
            hot, coldd = pt.split_delta(
                self.dense, state, delta, self.P, still_cold
            )
            if hot is not None:
                state = apply_any_delta(self.dense, state, hot)
                self.metrics.count("pager.queue_drains")
            if coldd is not None:
                self._queued.append((frozenset(still_cold), coldd))
        return state

    # --- mixed-residency read surface ---------------------------------------

    def digest_entries_for(self, state: Any, parts: Sequence[int]) -> Dict[int, int]:
        """Per-partition digests against the LOGICAL state: live entries
        from the device state, cold entries from the cache — bit-equal to
        an all-resident `digest_entries` because a cold partition's
        content lives wholly in the substrate the cache was cut from."""
        want = [int(p) for p in parts]
        cold = self.cold_parts()
        live = [p for p in want if p not in cold]
        out = dict(pt.digest_entries(state, self.P, live)) if live else {}
        for p in want:
            if p in cold:
                out[p] = self._digests[p]
        return out

    def digest_vector(self, state: Any) -> np.ndarray:
        entries = self.digest_entries_for(state, range(self.P + 1))
        vec = np.zeros(self.P + 1, np.uint32)
        for part, crc in entries.items():
            vec[part] = crc
        return vec

    def psnap_payload(self, state: Any, part: int) -> bytes:
        """The dumps_dense psnap payload for any partition: cold answers
        straight from storage (no hydration), hot restricts the device
        state as the legacy path does."""
        part = int(part)
        if part != self.P and part in self.cold_parts():
            self.metrics.count("pager.blob_serves")
            return self._load_payload(part)
        return serial.dumps_dense(
            f"{self.name}_psnap",
            pt.restrict_psnap(self.dense, state, part, self.P),
        )

    def psnap_blob(self, state: Any, seq: int, part: int) -> bytes:
        return pt.encode_psnap_blob(seq, part, self.psnap_payload(state, part))

    def full_state(self, state: Any) -> Any:
        """The logical state: device ⊔ cold substrate. Used at anchor
        publishes, serve swaps, checkpoints, and reference compares.
        Does not change residency."""
        import jax
        import jax.numpy as jnp

        if not self.has_cold() or self._cold is None:
            return state
        # Fresh default-device copy of the substrate: merge_into donates
        # the incoming operand, and the substrate must survive.
        cold_dev = jax.tree_util.tree_map(
            lambda x: jnp.asarray(np.asarray(x)), self._cold
        )
        self.metrics.count("pager.full_joins")
        return merge_into(
            self.dense.merge, state, cold_dev, site="pager.full_join"
        )

    # --- payload tiers (RAM -> disk) ----------------------------------------

    def _store_payload(self, part: int, payload: bytes) -> None:
        path = self._spilled.pop(part, None)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._payloads[part] = payload
        self._enforce_host_budget()

    def _enforce_host_budget(self) -> None:
        if not (self.host_budget and self.spill_dir):
            return
        while self.host_bytes() > self.host_budget and self._payloads:
            # Spill the least-recently-touched payload first.
            part = min(self._payloads, key=lambda p: (self._ref.get(p, 0), p))
            path = os.path.join(
                self.spill_dir, f"{SPILL_PREFIX}{self.name}-{part:05d}.ccpt"
            )
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(self._payloads[part])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._spilled[part] = path
            del self._payloads[part]
            self.metrics.count("pager.spills")

    def _load_payload(self, part: int) -> bytes:
        blob = self._payloads.get(part)
        if blob is not None:
            return blob
        path = self._spilled.get(part)
        if path is None:
            raise KeyError(f"partition {part} has no cold payload")
        with open(path, "rb") as f:
            return f.read()

    def _drop_payload(self, part: int) -> None:
        self._payloads.pop(part, None)
        path = self._spilled.pop(part, None)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    # --- observability -------------------------------------------------------

    def _export(self) -> None:
        m = self.metrics
        m.set("pager.resident_parts", len(self.resident))
        m.set("pager.resident_bytes", self.resident_bytes())
        m.set("pager.cold_parts", len(self.universe) - len(self.resident))
        m.set("pager.host_bytes", self.host_bytes())
        m.set("pager.spilled_parts", len(self._spilled))
        if devprof.ACTIVE:
            # HBM occupancy vs CCRDT_PAGER_HBM_BUDGET + high-watermark,
            # into the device observatory's own metrics registry.
            devprof.note_pager(self.resident_bytes(), self.hbm_budget)

    def export_gauges(self) -> None:
        self._export()

    def counters(self) -> Dict[str, int]:
        snap = self.metrics.snapshot()["counters"]
        return {
            k: int(v) for k, v in snap.items() if k.startswith("pager.")
        }

    def health_fields(self) -> Dict[str, Any]:
        return {
            "pager_resident_parts": len(self.resident),
            "pager_cold_parts": len(self.universe) - len(self.resident),
            "pager_resident_bytes": self.resident_bytes(),
            "pager_hbm_budget": self.hbm_budget,
            "pager_host_bytes": self.host_bytes(),
            "pager_spilled_parts": len(self._spilled),
            "pager_hit_rate": round(self.hit_rate(), 4),
            "pager_evictions": int(
                self.metrics.counters.get("pager.evictions", 0)
            ),
            "pager_hydrations": int(
                self.metrics.counters.get("pager.hydrations", 0)
            ),
            "pager_cold_folds": int(
                self.metrics.counters.get("pager.cold_folds", 0)
            ),
        }

    def status_fields(self) -> Dict[str, Any]:
        """The dashboard drop (`pager r:N/B` column in obs_dashboard)."""
        return {
            "resident_parts": len(self.resident),
            "total_parts": len(self.universe),
            "resident_bytes": self.resident_bytes(),
            "hit_rate": round(self.hit_rate(), 4),
        }
