"""The partition plane: fixed hash partitions over each instance's id space.

Big(ger) Sets (arxiv 1605.06424, PAPERS.md) decomposes one large CRDT
into independently replicated partitions so anti-entropy, digests, and
rejoin streaming operate on slices instead of the whole instance. This
module is the single source of truth for that decomposition:

* ``part_of(ids, P)`` — the stable id→partition map (Knuth multiplicative
  hash; NEVER python ``hash()``, which is salted per process).
* a per-engine *item plan* describing which leaf axes are item-indexed:
  - ``TopkRmvDenseState``: the I axis (axis 2) of the slot/tombstone
    leaves; ``vc``/``lossy`` are whole-instance.
  - table engines (topk / leaderboard / wordcount): the last axis of
    every 3-D ``[R, NK, P]`` plane; other leaves are whole-instance.
  - ``LiftedMonoidState``: the replica-row axis (axis 0) of every inner
    leaf plus ``ver`` — a row is the row-replace unit, so a partition of
    rows is the finest slice the lifted join can exchange.
* ``state_digests`` — a ``P+1``-entry crc32 vector. Index ``P`` is the
  **meta partition**: the whole-instance leaves (vc, lossy, loss
  counters...). Isolating them keeps one divergent id from dirtying every
  digest while still making whole-leaf drift detectable and cheap to
  repair (meta payloads are O(R·NK), not O(I)).
* ``restrict_psnap`` / ``apply_psnap`` — partial snapshots. A psnap is
  delta-SHAPED (`TopkRmvDelta`, the table-delta dict, or a monoid row
  delta) restricted to one partition, so the existing expand+join /
  row-replace machinery applies it and ``like_delta_for`` decodes it; no
  new kernels. Join semantics make application idempotent and
  order-free: merging a peer's psnap for partition p yields a local
  state ⊇ the peer's state on p.
* ``delta_parts`` — the partition set a decoded delta touches (computed
  receiver-side; deltas need no wire change to "carry" their partitions).
* the ``CCPT`` blob container for digest vectors, psnaps, and checkpoint
  shards — first-bytes magic disambiguation mirroring ``topo/codec.py``'s
  bare-ETF fallback, so legacy whole-instance blobs keep decoding.

Digest contract: two states with equal leaves have equal digest vectors,
and a state change confined to ids of partition p (resp. whole leaves)
perturbs only entry p (resp. entry P). The whole-instance digest
disagrees iff some vector entry disagrees.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

# Fibonacci/Knuth multiplicative constant: stable across processes,
# well-mixed low bits after the multiply for power-of-two P too.
_KNUTH = np.uint64(2654435761)
_MASK32 = np.uint64(0xFFFFFFFF)

DEFAULT_PARTITIONS = 8


def n_partitions(default: int = DEFAULT_PARTITIONS) -> int:
    """The fleet-wide partition count, env-tunable (``CCRDT_PARTITIONS``).
    Every member of a fleet must agree on it (it is a wire/digest
    parameter, like R or I)."""
    try:
        p = int(os.environ.get("CCRDT_PARTITIONS", default))
    except ValueError:
        p = default
    return max(1, p)


def part_of(ids: Any, P: int) -> np.ndarray:
    """Stable id→partition map, vectorized. int array in, int32 out."""
    a = np.asarray(ids, np.int64).astype(np.uint64)
    return (((a * _KNUTH) & _MASK32) % np.uint64(P)).astype(np.int32)


def meta_part(P: int) -> int:
    """Index of the meta partition (whole-instance leaves) in a
    ``P+1``-entry digest vector."""
    return P


# --- per-engine item plans -------------------------------------------------


def _is_topk_rmv(state: Any) -> bool:
    from ..models.topk_rmv_dense import TopkRmvDenseState

    return isinstance(state, TopkRmvDenseState)


def _is_lifted(state: Any) -> bool:
    from ..parallel.monoid import LiftedMonoidState

    return isinstance(state, LiftedMonoidState)


def _item_plan(state: Any) -> Tuple[List[Tuple[str, Any, int]], List[Tuple[str, Any]], int]:
    """((path, leaf, item_axis)[], (path, whole_leaf)[], item_count).

    The item axis is the axis whose index IS the partitionable id; all
    item leaves of one state share its extent (checked)."""
    import jax

    if _is_topk_rmv(state):
        I = int(state.slot_score.shape[2])
        items = [
            ("slot_score", state.slot_score, 2),
            ("slot_dc", state.slot_dc, 2),
            ("slot_ts", state.slot_ts, 2),
            ("rmv_vc", state.rmv_vc, 2),
        ]
        whole = [("vc", state.vc), ("lossy", state.lossy)]
        return items, whole, I
    if _is_lifted(state):
        R = int(state.ver.shape[0])
        flat = jax.tree_util.tree_flatten_with_path(state.inner)[0]
        items = [(jax.tree_util.keystr(p), leaf, 0) for p, leaf in flat]
        items.append((".ver", state.ver, 0))
        return items, [], R
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    items, whole, extent = [], [], None
    for p, leaf in flat:
        path = jax.tree_util.keystr(p)
        if leaf.ndim == 3:
            items.append((path, leaf, 2))
            n = int(leaf.shape[2])
            if extent is None:
                extent = n
            elif extent != n:
                raise ValueError(
                    f"table planes disagree on item extent: {extent} vs {n}"
                )
        else:
            whole.append((path, leaf))
    return items, whole, (extent or 0)


# --- per-partition digest vectors ------------------------------------------


def digest_entries(
    state: Any, P: int, parts: Sequence[int]
) -> Dict[int, int]:
    """crc32 digest entries for a SUBSET of partitions — ``{part: crc}``,
    ``part == P`` being the meta partition. This is the byte walk
    `state_digests` runs for every entry, exposed per-partition so a
    mesh shard (mesh/plan.py) can produce exactly the slice of the
    vector it owns; slices stitched back together are bitwise equal to
    the full vector because they ARE the full vector's entries."""
    items, whole, extent = _item_plan(state)
    id_parts = part_of(np.arange(extent), P) if extent else np.zeros(0, np.int32)
    host_items = [(path, np.asarray(leaf), axis) for path, leaf, axis in items]
    out: Dict[int, int] = {}
    for part in parts:
        part = int(part)
        crc = 0
        if part == P:
            for path, leaf in whole:
                arr = np.ascontiguousarray(np.asarray(leaf))
                crc = zlib.crc32(arr.tobytes(), zlib.crc32(path.encode(), crc))
        else:
            idx = np.nonzero(id_parts == part)[0]
            for path, leaf, axis in host_items:
                sl = np.ascontiguousarray(np.take(leaf, idx, axis=axis))
                crc = zlib.crc32(sl.tobytes(), zlib.crc32(path.encode(), crc))
        out[part] = crc & 0xFFFFFFFF
    return out


def state_digests(state: Any, P: int) -> np.ndarray:
    """``uint32[P+1]`` crc32 digest vector; entry P is the meta partition
    (whole-instance leaves). Pure function of the state's leaves."""
    entries = digest_entries(state, P, range(P + 1))
    vec = np.zeros(P + 1, np.uint32)
    for part, crc in entries.items():
        vec[part] = crc
    return vec


def divergent_parts(a: Any, b: Any) -> List[int]:
    """Indices where two digest vectors disagree (length mismatch = all)."""
    av, bv = np.asarray(a), np.asarray(b)
    if av.shape != bv.shape:
        return list(range(max(av.size, bv.size)))
    return [int(i) for i in np.nonzero(av != bv)[0]]


class DigestSampler:
    """Bounded-cadence memo of `state_digests` for the audit plane.

    `state_digests` is a host-side crc sweep over every leaf — cheap at
    anchor cadence, not at per-round watchdog cadence. The sampler
    memoizes the vector keyed on the caller's own progress seq (the
    publisher seq: the state cannot have changed without it advancing)
    and, when no seq is available, rate-limits recomputation to
    `min_interval_s` on the monotonic clock. The staleness this trades
    is exactly one publish interval — the same freshness the digest a
    PEER fetched has, so watchdog comparisons stay apples-to-apples."""

    def __init__(
        self, P: Optional[int] = None, min_interval_s: float = 0.25,
        mono: Any = None,
    ) -> None:
        import time

        self.P = P if P else n_partitions()
        self.min_interval_s = float(min_interval_s)
        self._mono = mono if mono is not None else time.monotonic
        self._seq: Optional[int] = None
        self._at: float = float("-inf")
        self._vec: Optional[np.ndarray] = None
        self.computes = 0  # recomputation count (bench: sampling cost)

    def sample(self, state: Any, seq: Optional[int] = None) -> np.ndarray:
        now = self._mono()
        if self._vec is not None:
            if seq is not None and seq == self._seq:
                return self._vec
            if seq is None and now - self._at < self.min_interval_s:
                return self._vec
        self._vec = state_digests(state, self.P)
        self._seq, self._at = seq, now
        self.computes += 1
        return self._vec

    def invalidate(self) -> None:
        """Force the next `sample` to recompute (e.g. after applying a
        repair outside the seq axis)."""
        self._seq, self._at, self._vec = None, float("-inf"), None


# --- partition-restricted partial snapshots (psnaps) -----------------------


def restrict_psnap(dense: Any, state: Any, part: int, P: int) -> Any:
    """The slice of `state` belonging to partition `part`, as a
    delta-shaped payload (apply with ``apply_psnap`` / decode against
    ``parallel.delta.like_delta_for``). ``part == P`` is the meta
    partition: whole-instance leaves with an empty item slice."""
    import jax.numpy as jnp

    from ..core.behaviour import MergeKind
    from ..parallel.delta import TopkRmvDelta, _split_leaves

    meta = part == P
    if _is_topk_rmv(state):
        R, NK, I, M = state.slot_score.shape
        D = state.rmv_vc.shape[-1]
        if meta:
            rows = np.zeros(0, np.int64)
        else:
            ids = np.nonzero(part_of(np.arange(I), P) == part)[0]
            # all (r, k) rows for the partition's ids; identity rows are
            # dropped (they join as no-ops and only cost bytes)
            rows = (
                np.arange(R * NK)[:, None] * I + ids[None, :]
            ).reshape(-1)
            score = np.asarray(state.slot_score).reshape(R * NK * I, M)[rows]
            dc = np.asarray(state.slot_dc).reshape(R * NK * I, M)[rows]
            ts = np.asarray(state.slot_ts).reshape(R * NK * I, M)[rows]
            rvc = np.asarray(state.rmv_vc).reshape(R * NK * I, D)[rows]
            from ..ops.dense_table import NEG_INF

            live = (
                np.any(score != NEG_INF, axis=1)
                | np.any(dc != 0, axis=1)
                | np.any(ts != 0, axis=1)
                | np.any(rvc != 0, axis=1)
            )
            rows = rows[live]
        flat = lambda x, w: np.asarray(x).reshape(R * NK * I, w)  # noqa: E731
        return TopkRmvDelta(
            rows=jnp.asarray(rows.astype(np.int32)),
            slot_score=jnp.asarray(flat(state.slot_score, M)[rows]),
            slot_dc=jnp.asarray(flat(state.slot_dc, M)[rows]),
            slot_ts=jnp.asarray(flat(state.slot_ts, M)[rows]),
            rmv_vc=jnp.asarray(flat(state.rmv_vc, D)[rows]),
            # zeros are the join identity for vc/lossy: a non-meta psnap
            # asserts nothing about the whole-instance leaves
            vc=state.vc if meta else jnp.zeros_like(state.vc),
            lossy=state.lossy if meta else jnp.zeros_like(state.lossy),
        )
    if _is_lifted(state):
        import jax

        R = int(state.ver.shape[0])
        if meta:
            rows = np.zeros(0, np.int64)
        else:
            rows = np.nonzero(part_of(np.arange(R), P) == part)[0]
        rj = jnp.asarray(rows.astype(np.int32))
        flat = jax.tree_util.tree_flatten_with_path(state.inner)[0]
        return {
            "rows": rj,
            "ver": state.ver[rj],
            "leaves": {
                jax.tree_util.keystr(p): leaf[rj] for p, leaf in flat
            },
        }
    if getattr(dense, "merge_kind", None) == MergeKind.MONOID:
        raise ValueError(
            "psnaps for bare MONOID engines are unsound (re-merge "
            "double-counts); gossip monoid engines through MonoidLift"
        )
    paths, leaves, table_paths, _ = _split_leaves(state)
    by_path = dict(zip(paths, leaves))
    extent = None
    for p in table_paths:
        extent = int(by_path[p].shape[2])
        break
    out: Dict[str, Any] = {"idx": None, "table": {}, "whole": {}}
    if meta or extent is None:
        idx = np.zeros(0, np.int64)
    else:
        ids = np.nonzero(part_of(np.arange(extent), P) == part)[0]
        lead = 1
        for p in table_paths:
            lead = int(np.prod(by_path[p].shape[:2]))
            break
        idx = (np.arange(lead)[:, None] * extent + ids[None, :]).reshape(-1)
    out["idx"] = jnp.asarray(idx.astype(np.int32))
    for p in paths:
        leaf = by_path[p]
        if p in table_paths:
            out["table"][p] = jnp.asarray(
                np.asarray(leaf).reshape(-1)[idx]
            )
        else:
            # identity (init) whole leaves unless this IS the meta psnap
            out["whole"][p] = leaf if meta else None
    if not meta:
        R, NK = leaves[0].shape[:2]
        ident = dense.init(R, NK)
        ipaths, ileaves, _, _ = _split_leaves(ident)
        ident_by = dict(zip(ipaths, ileaves))
        for p in list(out["whole"]):
            out["whole"][p] = ident_by[p]
    return out


def apply_psnap(dense: Any, state: Any, payload: Any) -> Any:
    """Join a psnap payload into `state` (idempotent; order-free)."""
    from ..parallel.delta import apply_any_delta

    return apply_any_delta(dense, state, payload)


def delta_parts(dense: Any, like_state: Any, delta: Any, P: int) -> Set[int]:
    """The partitions a decoded delta touches — computed receiver-side,
    so deltas "carry" their partition set with no wire change. JOIN
    deltas always touch the meta partition (they ship vc/whole leaves)."""
    from ..parallel.delta import TopkRmvDelta, _is_monoid_row_delta

    if isinstance(delta, TopkRmvDelta):
        I = dense.I
        ids = np.asarray(delta.rows) % I
        return set(int(x) for x in np.unique(part_of(ids, P))) | {P}
    if _is_monoid_row_delta(delta):
        rows = np.asarray(delta["rows"])
        return set(int(x) for x in np.unique(part_of(rows, P)))
    items, _, extent = _item_plan(like_state)
    idx = np.asarray(delta.get("idx", np.zeros(0, np.int64)))
    if extent:
        ids = idx % extent
        parts = set(int(x) for x in np.unique(part_of(ids, P)))
    else:
        parts = set()
    return parts | {P}


def split_delta(
    dense: Any, like_state: Any, delta: Any, P: int, parts: Sequence[int]
) -> Tuple[Any, Optional[Any]]:
    """Split a decoded delta (or delta-shaped psnap) into ``(hot, cold)``
    halves around a partition set: rows/entries whose id hashes into
    `parts` go to the cold half, everything else stays hot. The meta
    payload (vc / whole leaves) rides the HOT half — the meta partition
    is pinned resident by the pager — and the cold half asserts nothing
    about it (join-identity leaves, same move as a non-meta psnap).
    Either return slot may be the original delta / None when one side is
    empty. Joining both halves into the same state equals joining the
    original delta: the split is along the item axis, where every leaf
    row joins independently.

    Lifted monoid row deltas are rejected: a lifted state partitions by
    replica row, which the pager does not page (core/pager.py)."""
    import jax
    import jax.numpy as jnp

    from ..parallel.delta import TopkRmvDelta, _is_monoid_row_delta, _split_leaves

    cold_set = np.asarray(sorted(int(p) for p in parts), np.int64)
    if cold_set.size == 0:
        return delta, None
    if isinstance(delta, TopkRmvDelta):
        rows = np.asarray(delta.rows)
        in_cold = np.isin(part_of(rows % dense.I, P), cold_set)
        if not in_cold.any():
            return delta, None

        def _take(mask: np.ndarray) -> Dict[str, Any]:
            sel = np.nonzero(mask)[0]
            return {
                "rows": jnp.asarray(rows[sel].astype(np.int32)),
                "slot_score": jnp.asarray(np.asarray(delta.slot_score)[sel]),
                "slot_dc": jnp.asarray(np.asarray(delta.slot_dc)[sel]),
                "slot_ts": jnp.asarray(np.asarray(delta.slot_ts)[sel]),
                "rmv_vc": jnp.asarray(np.asarray(delta.rmv_vc)[sel]),
            }

        hot = TopkRmvDelta(**_take(~in_cold), vc=delta.vc, lossy=delta.lossy)
        cold = TopkRmvDelta(
            **_take(in_cold),
            vc=jnp.zeros_like(delta.vc),
            lossy=jnp.zeros_like(delta.lossy),
        )
        return hot, cold
    if _is_monoid_row_delta(delta):
        raise ValueError("cannot split a lifted monoid row delta by partition")
    _items, _whole, extent = _item_plan(like_state)
    idx = np.asarray(delta.get("idx", np.zeros(0, np.int64)))
    if extent == 0 or idx.size == 0:
        return delta, None
    in_cold = np.isin(part_of(idx % extent, P), cold_set)
    if not in_cold.any():
        return delta, None
    hot_sel = np.nonzero(~in_cold)[0]
    cold_sel = np.nonzero(in_cold)[0]
    hot = {
        "idx": jnp.asarray(idx[hot_sel].astype(np.int32)),
        "table": {
            p: jnp.asarray(np.asarray(v)[hot_sel])
            for p, v in delta["table"].items()
        },
        "whole": dict(delta["whole"]),
    }
    R, NK = jax.tree_util.tree_leaves(like_state)[0].shape[:2]
    ipaths, ileaves, _t, _ = _split_leaves(dense.init(R, NK))
    ident_by = dict(zip(ipaths, ileaves))
    cold = {
        "idx": jnp.asarray(idx[cold_sel].astype(np.int32)),
        "table": {
            p: jnp.asarray(np.asarray(v)[cold_sel])
            for p, v in delta["table"].items()
        },
        "whole": {p: ident_by[p] for p in delta["whole"]},
    }
    return hot, cold


# --- CCPT blob container ---------------------------------------------------
# First-bytes disambiguation, same move as topo/codec.py's bare-ETF
# fallback: new blobs open with b"CCPT"; legacy whole-instance snapshot
# blobs open with an 8-byte step header followed by serial.MAGIC
# (b"CCRD" at offset 8). `is_partition_blob` keys the dispatch.

PART_MAGIC = b"CCPT"
# Version 1: raw payload. Version 2: zlib-deflated payload (psnaps only
# — the 18-byte header stays uncompressed so `seq` keeps parsing at a
# fixed offset on every transport). The encoder picks whichever is
# smaller per blob; decoders accept both, so v1 artifacts (old
# checkpoint shards, mixed-version peers) stay readable.
PART_VERSION = 2
KIND_DIGESTS = 0
KIND_PSNAP = 1


def is_partition_blob(blob: bytes) -> bool:
    return bytes(blob[:4]) == PART_MAGIC


def encode_digest_blob(seq: int, vec: Any) -> bytes:
    # Digest vectors are 4(P+1) bytes — deflate cannot help, write v1.
    v = np.asarray(vec, np.uint32)
    return (
        PART_MAGIC
        + bytes([1, KIND_DIGESTS])
        + struct.pack("<QI", int(seq), int(v.size))
        + v.astype("<u4").tobytes()
    )


def decode_digest_blob(blob: bytes) -> Tuple[int, np.ndarray]:
    _check_header(blob, KIND_DIGESTS)
    seq, n = struct.unpack_from("<QI", blob, 6)
    vec = np.frombuffer(blob, dtype="<u4", count=n, offset=18).astype(np.uint32)
    return int(seq), vec


def encode_psnap_blob(seq: int, part: int, dense_payload: bytes) -> bytes:
    """`dense_payload` is a ``serial.dumps_dense`` blob of the restricted
    delta-shaped psnap. The flat-serial envelope (leaf paths, dtypes)
    dominates small psnaps — a meta psnap is ~70 bytes of arrays in a
    ~2 KB blob — so the payload ships deflated (v2) whenever that is
    actually smaller, raw (v1) otherwise."""
    header = struct.pack("<QI", int(seq), int(part))
    packed = zlib.compress(dense_payload, 6)
    if len(packed) < len(dense_payload):
        return PART_MAGIC + bytes([2, KIND_PSNAP]) + header + packed
    return PART_MAGIC + bytes([1, KIND_PSNAP]) + header + dense_payload


def decode_psnap_blob(blob: bytes) -> Tuple[int, int, bytes]:
    """(seq, part, dense_payload). Accepts v1 (raw) and v2 (deflated)."""
    _check_header(blob, KIND_PSNAP)
    seq, part = struct.unpack_from("<QI", blob, 6)
    payload = bytes(blob[18:])
    if blob[4] >= 2:
        payload = zlib.decompress(payload)
    return int(seq), int(part), payload


def _check_header(blob: bytes, kind: int) -> None:
    if not is_partition_blob(blob):
        raise ValueError("not a CCPT partition blob (bad magic)")
    version, k = blob[4], blob[5]
    if version > PART_VERSION:
        raise ValueError(
            f"partition blob version {version} newer than supported "
            f"{PART_VERSION}"
        )
    if k != kind:
        raise ValueError(f"partition blob kind {k} != expected {kind}")
