"""Erlang External Term Format (ETF) codec — wire parity with the reference.

The reference serializes every CRDT state with ``term_to_binary`` /
``binary_to_term`` (e.g. ``antidote_ccrdt_topk_rmv.erl:156-163``,
``antidote_ccrdt_wordcount.erl:59-64``). This module implements the subset
of ETF those states use, so snapshots written by a real Antidote/BEAM node
can be loaded into this framework and vice versa:

* integers (small / 32-bit / bignum), new floats, atoms (all three atom
  tags on decode, SMALL_ATOM_UTF8 on encode — what modern OTP emits),
  tuples, nil / proper lists / STRING_EXT byte-lists, binaries, maps,
  and zlib-compressed terms (decode).

Python <-> Erlang mapping:

    int    <-> SMALL_INTEGER/INTEGER/SMALL_BIG/LARGE_BIG
    float  <-> NEW_FLOAT
    Atom   <-> atom (Atom is a str subclass; ``Atom('nil')`` etc.)
    bytes  <-> BINARY
    str    -->  BINARY (utf-8); decode always yields bytes
    tuple  <-> SMALL_TUPLE/LARGE_TUPLE
    list   <-> NIL/LIST/STRING (STRING decodes to a list of ints,
               preserving Erlang's list-of-bytes semantics)
    dict   <-> MAP (encode orders keys by Erlang term order, matching
               how OTP flatmaps serialize — canonical bytes for <=32 keys)

Container helpers for the two stdlib structures reference states embed:

* ``gb_sets`` — ``{Size, Tree}`` with ``Tree = {Key, Smaller, Bigger} | nil``.
  ``gb_set_from_list`` rebuilds the exact balanced tree of
  ``gb_sets:from_ordset`` (the deterministic complete-tree construction),
  so encode(decode(x)) is byte-stable for sets built that way.
* ``sets`` — decode supports both the pre-OTP-24 record form (``{set, ...}``
  walked structurally) and the OTP-24+ map form (``#{Elem => []}``);
  encode always emits the map form (v2), which ``sets:is_element/2`` et al.
  accept on any modern OTP.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Iterable, List, Tuple

VERSION_MAGIC = 131

# Term tags (subset).
NEW_FLOAT_EXT = 70
COMPRESSED = 80
SMALL_INTEGER_EXT = 97
INTEGER_EXT = 98
FLOAT_EXT = 99
ATOM_EXT = 100
SMALL_TUPLE_EXT = 104
LARGE_TUPLE_EXT = 105
NIL_EXT = 106
STRING_EXT = 107
LIST_EXT = 108
BINARY_EXT = 109
SMALL_BIG_EXT = 110
LARGE_BIG_EXT = 111
MAP_EXT = 116
ATOM_UTF8_EXT = 118
SMALL_ATOM_UTF8_EXT = 119


class Atom(str):
    """An Erlang atom. Equality and hashing are type-strict: ``Atom('x') !=
    'x'`` and the two can coexist as distinct dict keys, mirroring how the
    atom ``x`` and the binary ``<<"x">>`` are distinct Erlang terms (ids
    decode utf-8 binaries to plain str, so without this a state keyed by
    both would silently merge)."""

    __slots__ = ()

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Atom):
            return str.__eq__(self, other)
        return NotImplemented if not isinstance(other, str) else False

    def __ne__(self, other: Any) -> bool:
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __hash__(self) -> int:
        return hash(("\x00erlang-atom", str(self)))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Atom({str.__repr__(self)})"


NIL_ATOM = Atom("nil")


# --- encode ---------------------------------------------------------------


def _term_rank(x: Any) -> int:
    """Erlang term-order rank for the subset we encode:
    number < atom < tuple < map < nil/list < binary."""
    if isinstance(x, (int, float)) and not isinstance(x, bool):
        return 0
    if isinstance(x, (Atom, bool)):
        return 1
    if isinstance(x, tuple):
        return 2
    if isinstance(x, dict):
        return 3
    if isinstance(x, list):
        return 4
    if isinstance(x, (bytes, str)):
        return 5
    raise TypeError(f"not an encodable term: {type(x)!r}")


def _term_sort_key(x: Any):
    r = _term_rank(x)
    if r == 0:
        return (r, x)
    if r == 1:
        if isinstance(x, bool):
            return (r, "true" if x else "false")
        return (r, str(x))
    if r == 2:
        return (r, len(x), tuple(_term_sort_key(e) for e in x))
    if r == 3:
        return (r, len(x), tuple(sorted(_term_sort_key(k) for k in x)))
    if r == 4:
        return (r, tuple(_term_sort_key(e) for e in x))
    b = x.encode("utf-8") if isinstance(x, str) else x
    return (r, b)


def _enc_int(n: int, out: bytearray) -> None:
    if 0 <= n <= 255:
        out.append(SMALL_INTEGER_EXT)
        out.append(n)
    elif -(1 << 31) <= n < (1 << 31):
        out.append(INTEGER_EXT)
        out += struct.pack(">i", n)
    else:
        sign = 1 if n < 0 else 0
        mag = -n if sign else n
        b = mag.to_bytes((mag.bit_length() + 7) // 8, "little")
        if len(b) <= 255:
            out.append(SMALL_BIG_EXT)
            out.append(len(b))
        else:
            out.append(LARGE_BIG_EXT)
            out += struct.pack(">I", len(b))
        out.append(sign)
        out += b


def _enc(term: Any, out: bytearray) -> None:
    if isinstance(term, bool):
        # Erlang booleans are the atoms true/false.
        _enc(Atom("true" if term else "false"), out)
    elif isinstance(term, Atom):
        b = term.encode("utf-8")
        if len(b) <= 255:
            out.append(SMALL_ATOM_UTF8_EXT)
            out.append(len(b))
        else:
            out.append(ATOM_UTF8_EXT)
            out += struct.pack(">H", len(b))
        out += b
    elif isinstance(term, int):
        _enc_int(term, out)
    elif isinstance(term, float):
        out.append(NEW_FLOAT_EXT)
        out += struct.pack(">d", term)
    elif isinstance(term, (bytes, str)):
        b = term.encode("utf-8") if isinstance(term, str) else term
        out.append(BINARY_EXT)
        out += struct.pack(">I", len(b))
        out += b
    elif isinstance(term, tuple):
        if len(term) <= 255:
            out.append(SMALL_TUPLE_EXT)
            out.append(len(term))
        else:
            out.append(LARGE_TUPLE_EXT)
            out += struct.pack(">I", len(term))
        for x in term:
            _enc(x, out)
    elif isinstance(term, list):
        if not term:
            out.append(NIL_EXT)
            return
        if all(isinstance(x, int) and not isinstance(x, bool) and 0 <= x <= 255 for x in term) and len(term) <= 65535:
            # Erlang encodes lists of bytes as STRING_EXT; match it so our
            # bytes are identical to term_to_binary's.
            out.append(STRING_EXT)
            out += struct.pack(">H", len(term))
            out += bytes(term)
            return
        out.append(LIST_EXT)
        out += struct.pack(">I", len(term))
        for x in term:
            _enc(x, out)
        out.append(NIL_EXT)
    elif isinstance(term, dict):
        out.append(MAP_EXT)
        out += struct.pack(">I", len(term))
        # Canonical key order = Erlang term order (how OTP flatmaps with
        # <=32 keys serialize). For bigger maps OTP uses hash order, which
        # we cannot (and need not) reproduce — any order decodes fine.
        for k in sorted(term.keys(), key=_term_sort_key):
            _enc(k, out)
            _enc(term[k], out)
    else:
        raise TypeError(f"cannot encode {type(term)!r} as an Erlang term")


def encode(term: Any, compressed: bool = False) -> bytes:
    """``term_to_binary/1`` for the supported subset."""
    out = bytearray()
    _enc(term, out)
    if compressed:
        z = zlib.compress(bytes(out))
        if len(z) + 5 < len(out):
            return bytes([VERSION_MAGIC, COMPRESSED]) + struct.pack(">I", len(out)) + z
    return bytes([VERSION_MAGIC]) + bytes(out)


# --- decode ---------------------------------------------------------------


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.data[self.pos : self.pos + n]
        if len(b) != n:
            raise ValueError("truncated ETF term")
        self.pos += n
        return b

    def u8(self) -> int:
        return self.read(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self.read(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.read(4))[0]


def _dec(r: _Reader) -> Any:
    tag = r.u8()
    if tag == SMALL_INTEGER_EXT:
        return r.u8()
    if tag == INTEGER_EXT:
        return struct.unpack(">i", r.read(4))[0]
    if tag == NEW_FLOAT_EXT:
        return struct.unpack(">d", r.read(8))[0]
    if tag == FLOAT_EXT:
        return float(r.read(31).split(b"\x00", 1)[0].decode("ascii"))
    if tag in (SMALL_BIG_EXT, LARGE_BIG_EXT):
        n = r.u8() if tag == SMALL_BIG_EXT else r.u32()
        sign = r.u8()
        mag = int.from_bytes(r.read(n), "little")
        return -mag if sign else mag
    if tag == ATOM_EXT:
        return _atom(r.read(r.u16()).decode("latin-1"))
    if tag == ATOM_UTF8_EXT:
        return _atom(r.read(r.u16()).decode("utf-8"))
    if tag == SMALL_ATOM_UTF8_EXT:
        return _atom(r.read(r.u8()).decode("utf-8"))
    if tag in (SMALL_TUPLE_EXT, LARGE_TUPLE_EXT):
        n = r.u8() if tag == SMALL_TUPLE_EXT else r.u32()
        return tuple(_dec(r) for _ in range(n))
    if tag == NIL_EXT:
        return []
    if tag == STRING_EXT:
        return list(r.read(r.u16()))
    if tag == LIST_EXT:
        n = r.u32()
        items = [_dec(r) for _ in range(n)]
        tail = _dec(r)
        if tail != []:
            raise ValueError("improper lists are not supported")
        return items
    if tag == BINARY_EXT:
        return r.read(r.u32())
    if tag == MAP_EXT:
        n = r.u32()
        out = {}
        for _ in range(n):
            k = _dec(r)
            out[_hashable(k)] = _dec(r)
        return out
    raise ValueError(f"unsupported ETF tag {tag}")


def _atom(name: str) -> Any:
    if name == "true":
        return True
    if name == "false":
        return False
    return Atom(name)


def _hashable(k: Any) -> Any:
    """Map keys must be hashable in Python: lists (including charlists from
    STRING_EXT) and dicts anywhere inside the key become tuples. States in
    the reference never use list keys, so this is a corner-case guard — it
    loses the list/tuple distinction on re-encode, not a round-trip path."""
    if isinstance(k, (list, tuple)):
        return tuple(_hashable(x) for x in k)
    if isinstance(k, dict):
        return tuple(
            (_hashable(kk), _hashable(vv))
            for kk, vv in sorted(k.items(), key=lambda kv: _term_sort_key(kv[0]))
        )
    return k


def decode(data: bytes) -> Any:
    """``binary_to_term/1`` for the supported subset."""
    if not data or data[0] != VERSION_MAGIC:
        raise ValueError("not an ETF term (bad version magic)")
    if len(data) < 2:
        raise ValueError("truncated ETF term")
    r = _Reader(data)
    r.u8()
    if r.data[r.pos] == COMPRESSED:
        r.u8()
        size = r.u32()
        z = zlib.decompressobj()
        plain = z.decompress(data[r.pos :])
        if len(plain) != size or z.unused_data or not z.eof:
            raise ValueError("bad compressed ETF payload")
        r = _Reader(plain)
        r.pos = 0
        term = _dec(r)
        if r.pos != len(plain):
            raise ValueError("trailing bytes after ETF term")
        return term
    term = _dec(r)
    if r.pos != len(data):
        raise ValueError("trailing bytes after ETF term")
    return term


# --- gb_sets --------------------------------------------------------------


def gb_set_to_list(term: Any) -> List[Any]:
    """Elements of a ``gb_sets:set()`` term ``{Size, Tree}``, in order."""
    size, tree = term
    out: List[Any] = []

    def walk(t: Any) -> None:
        if t == NIL_ATOM or t == []:
            return
        k, smaller, bigger = t
        walk(smaller)
        out.append(k)
        walk(bigger)

    walk(tree)
    if len(out) != size:
        raise ValueError(f"gb_set size {size} != {len(out)} elements")
    return out


def gb_set_from_list(items: Iterable[Any]) -> Tuple[int, Any]:
    """Build the ``{Size, Tree}`` term exactly as ``gb_sets:from_ordset/1``
    does (complete-tree construction over the sorted input)."""
    xs = sorted(items, key=_term_sort_key)

    def balance(lst: List[Any], s: int) -> Tuple[Any, List[Any]]:
        if s > 1:
            sm = s - 1
            s2 = sm // 2
            s1 = sm - s2
            t1, rest = balance(lst, s1)
            k, rest = rest[0], rest[1:]
            t2, rest = balance(rest, s2)
            return (k, t1, t2), rest
        if s == 1:
            return (lst[0], NIL_ATOM, NIL_ATOM), lst[1:]
        return NIL_ATOM, lst

    tree, rest = balance(xs, len(xs))
    assert not rest
    return (len(xs), tree)


# --- sets -----------------------------------------------------------------


def set_to_list(term: Any) -> List[Any]:
    """Elements of a ``sets:set()`` term — either the pre-OTP-24 record
    ``{set, Size, ..., Segs}`` (walked structurally, no hashing needed) or
    the OTP-24+ map form ``#{Elem => []}``."""
    if isinstance(term, dict):
        return list(term.keys())
    if isinstance(term, tuple) and len(term) == 9 and term[0] == Atom("set"):
        size = term[1]
        segs = term[8]
        out: List[Any] = []
        for seg in segs:
            for bucket in seg:
                out.extend(bucket)
        if len(out) != size:
            raise ValueError(f"sets record size {size} != {len(out)} elements")
        return out
    raise ValueError("not a sets:set() term")


def set_from_list(items: Iterable[Any]) -> dict:
    """Encode as the OTP-24+ map form ``#{Elem => []}`` — accepted by the
    ``sets`` module on any modern OTP (version-2 sets)."""
    return {x: [] for x in items}
