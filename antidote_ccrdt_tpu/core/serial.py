"""Versioned state serialization.

The reference serializes whole states with ``term_to_binary`` /
``binary_to_term`` in every type (e.g. ``antidote_ccrdt_topk_rmv.erl:156-163``)
— no schema, no version tag. SURVEY.md §5 flags this for repair: snapshots
must carry enough header to survive format evolution.

Wire layout (little-endian):

    magic   b"CCRD"             4 bytes
    version u8                  format version (currently 1)
    kind    u8                  0 = scalar (msgpack-less python payload),
                                1 = dense (npz payload)
    name    u8 len + utf-8      registered type name
    payload rest

Scalar payloads are encoded with a small self-describing codec (no pickle:
pickle is neither stable across versions nor safe to load from an untrusted
replica). Dense payloads are ``np.savez`` archives of the pytree leaves plus
a JSON treedef manifest.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any

import numpy as np

MAGIC = b"CCRD"
VERSION = 1
KIND_SCALAR = 0
KIND_DENSE = 1

# --- scalar payload codec -------------------------------------------------
# Self-describing, canonical (sorted map keys), covering the value shapes
# scalar CRDT states use: ints, strings, bytes, floats, bools, None,
# tuples, lists, dicts, frozensets.

_T_NONE, _T_INT, _T_STR, _T_BYTES, _T_FLOAT, _T_BOOL = 0, 1, 2, 3, 4, 5
_T_TUPLE, _T_LIST, _T_DICT, _T_FSET = 6, 7, 8, 9


def _enc(obj: Any, out: io.BytesIO) -> None:
    if obj is None:
        out.write(bytes([_T_NONE]))
    elif isinstance(obj, bool):
        out.write(bytes([_T_BOOL, int(obj)]))
    elif isinstance(obj, int):
        b = obj.to_bytes((obj.bit_length() + 8) // 8 + 1, "little", signed=True)
        out.write(bytes([_T_INT]))
        out.write(struct.pack("<I", len(b)))
        out.write(b)
    elif isinstance(obj, float):
        out.write(bytes([_T_FLOAT]))
        out.write(struct.pack("<d", obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.write(bytes([_T_STR]))
        out.write(struct.pack("<I", len(b)))
        out.write(b)
    elif isinstance(obj, bytes):
        out.write(bytes([_T_BYTES]))
        out.write(struct.pack("<I", len(obj)))
        out.write(obj)
    elif isinstance(obj, tuple):
        out.write(bytes([_T_TUPLE]))
        out.write(struct.pack("<I", len(obj)))
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, list):
        out.write(bytes([_T_LIST]))
        out.write(struct.pack("<I", len(obj)))
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, dict):
        out.write(bytes([_T_DICT]))
        out.write(struct.pack("<I", len(obj)))
        for k in sorted(obj.keys(), key=repr):
            _enc(k, out)
            _enc(obj[k], out)
    elif isinstance(obj, frozenset):
        out.write(bytes([_T_FSET]))
        out.write(struct.pack("<I", len(obj)))
        for x in sorted(obj, key=repr):
            _enc(x, out)
    else:
        raise TypeError(f"unserializable scalar-state value: {type(obj)!r}")


def _dec(buf: io.BytesIO) -> Any:
    tag = buf.read(1)[0]
    if tag == _T_NONE:
        return None
    if tag == _T_BOOL:
        return bool(buf.read(1)[0])
    if tag == _T_INT:
        (n,) = struct.unpack("<I", buf.read(4))
        return int.from_bytes(buf.read(n), "little", signed=True)
    if tag == _T_FLOAT:
        return struct.unpack("<d", buf.read(8))[0]
    if tag == _T_STR:
        (n,) = struct.unpack("<I", buf.read(4))
        return buf.read(n).decode("utf-8")
    if tag == _T_BYTES:
        (n,) = struct.unpack("<I", buf.read(4))
        return buf.read(n)
    if tag == _T_TUPLE:
        (n,) = struct.unpack("<I", buf.read(4))
        return tuple(_dec(buf) for _ in range(n))
    if tag == _T_LIST:
        (n,) = struct.unpack("<I", buf.read(4))
        return [_dec(buf) for _ in range(n)]
    if tag == _T_DICT:
        (n,) = struct.unpack("<I", buf.read(4))
        return {(_dec(buf)): _dec(buf) for _ in range(n)}
    if tag == _T_FSET:
        (n,) = struct.unpack("<I", buf.read(4))
        return frozenset(_dec(buf) for _ in range(n))
    raise ValueError(f"bad tag {tag}")


def encode_term(obj: Any) -> bytes:
    """Bare canonical encoding of one python value (no snapshot header) —
    the framing used by op-log journals and the bridge wire protocol."""
    out = io.BytesIO()
    _enc(obj, out)
    return out.getvalue()


def decode_term(data: bytes) -> Any:
    buf = io.BytesIO(data)
    obj = _dec(buf)
    if buf.read(1):
        raise ValueError("trailing bytes after encoded term")
    return obj


def _header(kind: int, name: str) -> bytes:
    nb = name.encode("utf-8")
    return MAGIC + bytes([VERSION, kind, len(nb)]) + nb


def _parse_header(data: bytes) -> tuple[int, str, int]:
    if data[:4] != MAGIC:
        raise ValueError("not a CCRDT snapshot (bad magic)")
    version, kind, nlen = data[4], data[5], data[6]
    if version > VERSION:
        raise ValueError(f"snapshot version {version} is newer than supported {VERSION}")
    name = data[7 : 7 + nlen].decode("utf-8")
    return kind, name, 7 + nlen


def dumps_scalar(name: str, state: Any) -> bytes:
    out = io.BytesIO()
    out.write(_header(KIND_SCALAR, name))
    _enc(state, out)
    return out.getvalue()


def loads_scalar(data: bytes) -> tuple[str, Any]:
    kind, name, off = _parse_header(data)
    if kind != KIND_SCALAR:
        raise ValueError("snapshot is not a scalar state")
    return name, _dec(io.BytesIO(data[off:]))


def dumps_dense(name: str, state: Any) -> bytes:
    """Serialize a pytree of arrays: npz of leaves + JSON treedef manifest."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrs = {f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)}
    bio = io.BytesIO()
    np.savez(bio, manifest=np.frombuffer(
        json.dumps({"treedef": str(treedef), "n": len(leaves)}).encode(), dtype=np.uint8
    ), **arrs)
    return _header(KIND_DENSE, name) + bio.getvalue()


def peek_name(data: bytes) -> str:
    """The type name a dumps_scalar/dumps_dense blob was written under,
    without decoding the payload — the dispatch key for embedders that
    store heterogeneous snapshots (e.g. the bridge's grid restore)."""
    _kind, name, _off = _parse_header(bytes(data))
    return name


def loads_dense(data: bytes, like: Any) -> tuple[str, Any]:
    """Restore a dense state into the structure of `like` (same treedef)."""
    import jax

    kind, name, off = _parse_header(data)
    if kind != KIND_DENSE:
        raise ValueError("snapshot is not a dense state")
    npz = np.load(io.BytesIO(data[off:]))
    manifest = json.loads(bytes(npz["manifest"]).decode())
    _, treedef = jax.tree_util.tree_flatten(like)
    if manifest["n"] != treedef.num_leaves:
        raise ValueError(
            f"snapshot has {manifest['n']} leaves but target structure has "
            f"{treedef.num_leaves}"
        )
    if manifest["treedef"] != str(treedef):
        raise ValueError(
            f"snapshot treedef mismatch: stored {manifest['treedef']!r} vs "
            f"target {str(treedef)!r}"
        )
    leaves = [npz[f"leaf{i}"] for i in range(manifest["n"])]
    return name, jax.tree_util.tree_unflatten(treedef, leaves)
