"""Zone labels and the per-node map of who lives in which zone.

A zone is an opaque string naming a locality domain — a data center, a
TPU slice, a rack (`dc0`, `dc0/slice1`). Links inside a zone are cheap
(ICI/LAN); links between zones cross the DCN and are what `topo.router`
economizes. A member's own zone comes from explicit config or the
``CCRDT_ZONE`` env var (the same supervisor->worker propagation pattern
`CCRDT_FAULTS` / `CCRDT_OBS_DIR` use).

`ZoneMap` is deliberately LAST-WRITE-WINS and evidence-greedy: zones are
learned from static config (the demo's addr files), from `{hello}`
frames at link setup, and from the (member, zone) hop stamps on relayed
frames — whichever arrives first. A member whose zone is not (yet) known
maps to `UNKNOWN_ZONE`, and the router treats unknown-zone members as
LOCAL (direct gossip, full-mesh fallback): correctness must never wait
on zone discovery, only the traffic shape improves once it lands.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional

ENV_ZONE = "CCRDT_ZONE"
# Fleets that never configure zones all land here — one zone, so the
# router degenerates to exactly the old full-mesh behavior.
DEFAULT_ZONE = "z0"
# A member we have no zone evidence for. Routed as if local (see module
# docstring) and never counted as a zone of its own.
UNKNOWN_ZONE = "?"


def zone_from_env(
    env: Optional[Dict[str, str]] = None, default: str = DEFAULT_ZONE
) -> str:
    """This process's zone label from ``CCRDT_ZONE`` (or `default`)."""
    return (env if env is not None else os.environ).get(ENV_ZONE) or default


def slice_zone(index: int) -> str:
    """The canonical zone label for mesh slice `index` — how the mesh
    plane (mesh/) maps device-mesh slices onto the topo/ gossip
    topology: each mesh-sharded worker process is one slice, its
    CCRDT_ZONE is `slice_zone(i)`, and cross-slice anti-entropy rides
    the existing zone-aware routers (anchors, O(zones) crossings)
    unchanged. scripts/multichip_demo.py is the reference user."""
    return f"slice{int(index)}"


class ZoneMap:
    """member -> zone, shared by a transport and its router.

    Thread-safe: the TCP receive path learns zones from hello frames and
    path stamps on reader threads while the gossip loop routes on it."""

    def __init__(
        self,
        member: str,
        zone: str,
        zones: Optional[Dict[str, str]] = None,
    ):
        self.member = member
        self.zone = zone
        self._lock = threading.Lock()
        self._zones: Dict[str, str] = dict(zones or {})
        self._zones[member] = zone

    def learn(self, member: str, zone: str) -> bool:
        """Record that `member` lives in `zone`; returns True when this
        is new information. Self's zone is pinned at construction (a
        peer's claim about US is not evidence)."""
        if not member or not zone or zone == UNKNOWN_ZONE:
            return False
        if member == self.member:
            return False
        with self._lock:
            if self._zones.get(member) == zone:
                return False
            self._zones[member] = zone
            return True

    def zone_of(self, member: str) -> str:
        with self._lock:
            return self._zones.get(member, UNKNOWN_ZONE)

    def known(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._zones)

    def members_of(self, zone: str, candidates: Iterable[str]) -> List[str]:
        """`candidates` that live in `zone`, sorted."""
        with self._lock:
            return sorted(m for m in candidates if self._zones.get(m) == zone)

    def zones_of(self, candidates: Iterable[str]) -> List[str]:
        """Distinct known zones among `candidates` (self excluded unless
        listed), sorted. UNKNOWN members contribute no zone."""
        with self._lock:
            return sorted(
                {
                    z
                    for m in candidates
                    if (z := self._zones.get(m, UNKNOWN_ZONE)) != UNKNOWN_ZONE
                }
            )

    def group(self, members: Iterable[str]) -> Dict[str, List[str]]:
        """{zone: sorted members} over `members` (unknowns under '?')."""
        out: Dict[str, List[str]] = {}
        with self._lock:
            for m in members:
                out.setdefault(self._zones.get(m, UNKNOWN_ZONE), []).append(m)
        return {z: sorted(ms) for z, ms in sorted(out.items())}
