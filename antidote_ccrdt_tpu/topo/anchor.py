"""Deterministic per-zone anchor election via rendezvous (HRW) hashing.

One member per zone — the *anchor* — carries that zone's cross-DCN
traffic. The election needs three properties and nothing more:

* **Coordination-free.** Every member computes the anchor locally from
  its own alive view; two members with the same view always agree. No
  ballots, no terms, no leader lease — a transient view split just means
  two anchors relay for a round, and join-idempotence makes duplicate
  relays harmless.
* **Stable under churn.** Rendezvous hashing guarantees that removing a
  non-anchor never moves the anchor, and adding a member moves it only
  if the newcomer itself wins. Elections don't thrash while the fleet
  scales — only anchor death (or a bigger hash arriving) re-elects.
* **Fast failover.** The pool is the zone's ALIVE members, so the
  instant SWIM demotes the anchor to SUSPECT the runner-up takes over —
  within one membership round, well before DEAD is confirmed.

Scores are `sha1("zone|member")` — keyed by zone so a member that loses
the election in one zone layout isn't systematically unlucky elsewhere,
and stable across processes/runs (unlike `hash()`, which is salted).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional, Tuple


def anchor_rank(zone: str, member: str) -> Tuple[int, str]:
    """Rendezvous score of `member` for `zone`; max rank wins.

    The member name tie-breaks (sha1 collisions in 64 bits are
    negligible, but determinism must not hinge on that)."""
    h = hashlib.sha1(f"{zone}|{member}".encode("utf-8")).digest()
    return (int.from_bytes(h[:8], "big"), member)


def rendezvous_anchor(zone: str, members: Iterable[str]) -> Optional[str]:
    """The anchor for `zone` among `members`, or None if the pool is
    empty. Pure: same inputs, same anchor, on every node."""
    best: Optional[str] = None
    best_rank: Optional[Tuple[int, str]] = None
    for m in members:
        r = anchor_rank(zone, m)
        if best_rank is None or r > best_rank:
            best, best_rank = m, r
    return best


def rendezvous_order(key: str, members: Iterable[str]) -> List[str]:
    """The FULL rendezvous preference list for `key`: members sorted by
    descending HRW rank (so ``rendezvous_order(k, ms)[0] ==
    rendezvous_anchor(k, ms)``). This is the shared candidate ordering
    the serve-plane fleet router (`serve/router.py`) walks on failover:
    every client computes the same list from the same member set, and
    removing a dead candidate never reorders the survivors — the
    stability rendezvous hashing buys the anchor election buys query
    affinity too (the same key keeps hitting the same replica's hot-key
    cache until that replica actually dies)."""
    return sorted(members, key=lambda m: anchor_rank(key, m), reverse=True)
