"""Per-link delta-frame codecs: a codec byte ahead of the ETF payload.

Coded wire format (negotiated per-link via `{hello}` / `{hello_ack}`):

    frame := u32_be length ++ codec_byte ++ body
    codec_byte := 0 (raw) | 1 (zlib)

Interop with un-upgraded peers is free because the first byte of every
ETF term is the version magic 131: a length-framed payload starting with
131 is a LEGACY raw frame, 0/1 are coded frames, and nothing else is
valid. `decode_body` accepts all three, so a receiver never needs to
know what the sender negotiated; negotiation only decides what we SEND
(legacy peers must never receive a codec byte they'd feed to
`etf.decode`).

Compression is per-frame self-describing: a zlib link may still emit a
raw-tagged frame when deflate would grow it (tiny heartbeats, already-
dense blobs), so `net.codec_saved_bytes` counts only real wins. The
default policy (in the transports) is zlib on cross-zone links only —
intra-zone links are cheap, the DCN is not.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Optional

from ..core import etf

CODEC_RAW = 0
CODEC_ZLIB = 1

_ETF_MAGIC = 131  # first byte of every term_to_binary payload

# Same ceiling as bridge.protocol.MAX_FRAME — a decompressed body is
# re-checked against it so a hostile/corrupt zlib frame can't balloon.
MAX_FRAME = 256 * 1024 * 1024

# zlib level 6 is the size/speed knee; deltas are small ETF terms and
# the win comes from repeated atom/key structure, not deep entropy.
_ZLIB_LEVEL = 6


def encode_frame(payload: bytes, codec: int, metrics: Optional[Any] = None) -> bytes:
    """Length-frame `payload` (ETF bytes) under `codec`.

    CODEC_ZLIB falls back to a raw-tagged frame when compression does
    not shrink the body — the codec byte makes each frame
    self-describing, so the receiver never cares which way it went."""
    if codec == CODEC_ZLIB:
        squeezed = zlib.compress(payload, _ZLIB_LEVEL)
        if len(squeezed) < len(payload):
            if metrics is not None:
                metrics.count("net.codec_zlib_frames")
                metrics.count(
                    "net.codec_saved_bytes", len(payload) - len(squeezed)
                )
            body = bytes([CODEC_ZLIB]) + squeezed
            return struct.pack(">I", len(body)) + body
        codec = CODEC_RAW
    if codec != CODEC_RAW:
        raise ValueError(f"unknown codec {codec!r}")
    body = bytes([CODEC_RAW]) + payload
    return struct.pack(">I", len(body)) + body


def decode_body(body: bytes) -> bytes:
    """Coded (or legacy bare-ETF) frame body -> ETF payload bytes."""
    if not body:
        raise ValueError("empty frame body")
    tag = body[0]
    if tag == _ETF_MAGIC:
        return body  # legacy peer: bare ETF, no codec byte
    if tag == CODEC_RAW:
        return body[1:]
    if tag == CODEC_ZLIB:
        payload = zlib.decompress(body[1:])
        if len(payload) > MAX_FRAME:
            raise ValueError(
                f"decompressed frame of {len(payload)} bytes exceeds limit"
            )
        return payload
    raise ValueError(f"unknown frame codec byte {tag}")


def unpack_coded_frames(buf: bytearray):
    """Yield decoded terms from `buf`, consuming complete frames in
    place. Mirrors `bridge.protocol.unpack_frames` but tolerates coded
    AND legacy bodies, so one reader speaks to mixed fleets."""
    while True:
        if len(buf) < 4:
            return
        (n,) = struct.unpack(">I", bytes(buf[:4]))
        if n > MAX_FRAME:
            raise ValueError(f"frame of {n} bytes exceeds limit")
        if len(buf) < 4 + n:
            return
        body = bytes(buf[4 : 4 + n])
        del buf[: 4 + n]
        yield etf.decode(decode_body(body))
