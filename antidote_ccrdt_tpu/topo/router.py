"""Zone-aware routing policy: who a member actually gossips with.

Replaces the transports' flat "send to every peer" loop:

* **Leaves** gossip only intra-zone (plus any peer whose zone is still
  unknown — full-mesh fallback, correctness never waits on discovery).
* **Anchors** (one per zone, `topo.anchor`) additionally send to the
  anchors of every remote zone, so each frame crosses the DCN O(zones)
  times instead of O(peers).
* **Relays**: a routed frame carries a `path` of (member, zone) hop
  stamps, origin first, appended at every hop. The origin-zone anchor
  relays cross-zone to anchors of zones not yet in the path; a remote-
  zone anchor fans the frame out to its own zone-mates and stops. Each
  zone therefore enters the path at most once — loop-freedom by
  construction — and the flight log can replay the stamps as
  `leaf -> anchor -> anchor -> leaf`.

Elections re-run on every routing decision against the CURRENT alive
view: the moment SWIM demotes an anchor to SUSPECT it drops out of the
pool and the rendezvous runner-up takes over (failover within one
membership round). A transient split view just means two anchors relay
for a round — duplicate joins are idempotent. Membership is duck-typed
(`state_of(member, timeout_s) -> "alive"|"suspect"|"dead"`) so this
module never imports `net/` — the transports import us.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..obs import events as obs_events
from .anchor import rendezvous_anchor
from .zones import UNKNOWN_ZONE, ZoneMap

# Local copies of the SWIM state strings (net.membership defines the
# same values; importing them would create the cycle this package bans).
_ALIVE = "alive"
_DEAD = "dead"

# path stamp: (member, zone)
Stamp = Tuple[str, str]
# routing decision: (peer, crosses_a_zone_boundary)
Target = Tuple[str, bool]


class ZoneRouter:
    """One member's routing policy over a shared `ZoneMap`.

    Stateless between calls except for the per-zone anchor cache, which
    exists only to make failovers observable (`topo.anchor_change`
    events + `topo.anchor_changes` counter) — routing itself always
    recomputes from the live view."""

    def __init__(
        self,
        member: str,
        zone: str,
        zones: ZoneMap,
        membership: Optional[Any] = None,
        timeout_s: float = 2.0,
        metrics: Optional[Any] = None,
    ):
        self.member = member
        self.zone = zone
        self.zones = zones
        self.membership = membership
        self.timeout_s = timeout_s
        self.metrics = metrics
        self._anchors: Dict[str, str] = {}

    # -- election ------------------------------------------------------------

    def _pool(self, zone: str, candidates: Iterable[str]) -> List[str]:
        """Election pool for `zone`: its members among `candidates`
        (self included for its own zone), preferring ALIVE, degrading to
        not-DEAD, then to everyone known — during bootstrap nobody has
        been heard yet and an empty pool would leave zones anchorless."""
        members = set(self.zones.members_of(zone, candidates))
        if zone == self.zone:
            members.add(self.member)
        if not members:
            return []
        if self.membership is None:
            return sorted(members)
        states = {
            m: (
                _ALIVE
                if m == self.member
                else self.membership.state_of(m, self.timeout_s)
            )
            for m in members
        }
        for keep in (
            lambda s: s == _ALIVE,
            lambda s: s != _DEAD,
            lambda s: True,
        ):
            pool = sorted(m for m, s in states.items() if keep(s))
            if pool:
                return pool
        return []

    def anchor_of(self, zone: str, candidates: Iterable[str]) -> Optional[str]:
        """Current anchor of `zone`, re-elected from the live view.
        Emits `topo.anchor_change` (and counts `topo.anchor_changes`)
        on first election and every failover."""
        anchor = rendezvous_anchor(zone, self._pool(zone, candidates))
        if anchor is not None and self._anchors.get(zone) != anchor:
            old = self._anchors.get(zone)
            self._anchors[zone] = anchor
            obs_events.emit(
                "topo.anchor_change",
                member=self.member,
                zone=zone,
                old=old,
                new=anchor,
            )
            if self.metrics is not None:
                self.metrics.count("topo.anchor_changes")
        return anchor

    def is_anchor(self, candidates: Iterable[str]) -> bool:
        """Is self the current anchor of its own zone?"""
        return self.anchor_of(self.zone, candidates) == self.member

    def anchors(self, candidates: Sequence[str]) -> Dict[str, str]:
        """{zone: anchor} over every zone visible in `candidates` + own."""
        out: Dict[str, str] = {}
        for z in sorted(set(self.zones.zones_of(candidates)) | {self.zone}):
            a = self.anchor_of(z, candidates)
            if a is not None:
                out[z] = a
        return out

    # -- routing -------------------------------------------------------------

    def send_targets(self, candidates: Sequence[str]) -> List[Target]:
        """Where one of self's OWN frames goes.

        Always: zone-mates and unknown-zone peers, direct. If self is
        its zone's anchor, additionally the anchor of every remote zone
        (the O(zones) cross-DCN component)."""
        out: List[Target] = []
        for peer in sorted(candidates):
            if peer == self.member:
                continue
            pz = self.zones.zone_of(peer)
            if pz == self.zone or pz == UNKNOWN_ZONE:
                out.append((peer, False))
        if self.is_anchor(candidates):
            for z, anchor in self.anchors(candidates).items():
                if z != self.zone and anchor != self.member:
                    out.append((anchor, True))
        return out

    def plan_relay(
        self,
        origin: str,
        path: Sequence[Stamp],
        candidates: Sequence[str],
    ) -> List[Target]:
        """Where a frame from `origin`, already stamped with `path`,
        goes next. The caller appends its own stamp when forwarding.

        Only anchors relay. The origin-zone anchor fans cross-zone to
        anchors of unvisited zones; a remote-zone anchor fans out to its
        zone-mates not already on the path, and stops."""
        if not self.is_anchor(candidates):
            return []
        visited_members = {m for m, _ in path} | {origin, self.member}
        visited_zones = {z for _, z in path if z != UNKNOWN_ZONE}
        visited_zones.add(self.zone)
        origin_zone = self.zones.zone_of(origin)
        out: List[Target] = []
        if origin_zone == self.zone:
            for z, anchor in self.anchors(candidates).items():
                if z not in visited_zones and anchor not in visited_members:
                    out.append((anchor, True))
        else:
            for peer in self.zones.members_of(self.zone, candidates):
                if peer not in visited_members:
                    out.append((peer, False))
        return out

    @staticmethod
    def loop_safe(path: Sequence[Stamp], member: str) -> bool:
        """May `member` accept/forward a frame with this path? False
        when its own stamp is already present (a routing loop — drop)."""
        return all(m != member for m, _ in path)
