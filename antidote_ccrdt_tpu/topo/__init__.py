"""topo/: DCN-aware hierarchical gossip topology.

The `net/` tier gossips full-mesh: every delta crosses the (expensive,
high-latency) data-center network once per remote peer, so cross-DCN
traffic grows O(peers) — the scaling wall the ROADMAP names first. This
package layers a zone-aware topology UNDER the transports:

* `topo.zones`  — zone labels (`dc0`, `dc0/slice1`, ... from env or
  config) and the `ZoneMap` every node keeps of who lives where,
  learned from config, hello frames, and relay path stamps.
* `topo.anchor` — deterministic rendezvous-hash anchor election: one
  member per zone carries that zone's cross-DCN traffic. Stable under
  churn (removing a non-anchor never moves the anchor) and coordination-
  free (every member computes it locally from its own alive view).
* `topo.router` — the routing policy transports consult instead of the
  flat peer list: leaves gossip only intra-zone; anchors additionally
  relay to remote-zone anchors; relayed frames carry a (member, zone)
  hop stamp so no zone is ever entered twice (loop-free) and the flight
  log can reconstruct `leaf -> anchor -> anchor -> leaf` paths.
* `topo.codec`  — per-link delta-frame compression: a codec byte ahead
  of the ETF payload (0 = raw, 1 = zlib), negotiated per-link at hello
  so mixed fleets interop; default policy compresses cross-zone links
  only (intra-zone links are cheap, the DCN is not).

Correctness never depends on the topology: blobs land in the same
transport caches, anti-entropy stays join-based above, and a member with
an unknown zone simply degrades to full-mesh treatment. The topology
only changes WHERE frames travel — convergence is still pinned to the
full-mesh baseline digest by tests/test_topo_chaos.py and
`make topo-demo`.

This package must not import from `net/` (the transports import us).
"""

from .anchor import anchor_rank, rendezvous_anchor, rendezvous_order
from .codec import (
    CODEC_RAW,
    CODEC_ZLIB,
    decode_body,
    encode_frame,
    unpack_coded_frames,
)
from .router import ZoneRouter
from .zones import ENV_ZONE, UNKNOWN_ZONE, ZoneMap, zone_from_env

__all__ = [
    "ENV_ZONE",
    "UNKNOWN_ZONE",
    "ZoneMap",
    "zone_from_env",
    "anchor_rank",
    "rendezvous_anchor",
    "rendezvous_order",
    "ZoneRouter",
    "CODEC_RAW",
    "CODEC_ZLIB",
    "encode_frame",
    "decode_body",
    "unpack_coded_frames",
]
