"""wordcount / worddocumentcount: grow-only word -> count maps.

Reference: ``src/antidote_ccrdt_wordcount.erl`` and
``src/antidote_ccrdt_worddocumentcount.erl``. An ``add`` carries a document
(a string); the update splits it on ``"\\n"`` / ``" "`` and folds counts
(``wordcount.erl:76-85``). ``worddocumentcount`` dedupes words within the
document first (through a gb_set, ``worddocumentcount.erl:76-86``) so each
document contributes at most 1 per word. Downstream is stateless
(``wordcount.erl:50-51``).

Tokenization parity note: Erlang's ``binary:split(_, _, [global])`` keeps
empty segments, so consecutive separators yield empty-string "words" that
the reference counts. We reproduce that exactly (``re.split``).

Deliberate fix (SURVEY.md §2 quirk #3): the reference's ``compact_ops``
returns ``{noop, noop}`` — *discarding both ops* and silently losing data if
the host compacts (``wordcount.erl:70-72``). Word counts form a trivial
commutative monoid, so here compaction fuses the two ops into one
``add_counts`` op carrying the combined counts.

Dense design (SURVEY.md §7): hashed-vocabulary count table ``i32[R, V]``;
documents are tokenized host-side into hash ids, an op batch is one
bincount/segment-sum, and the cross-replica merge is ``+`` (MONOID).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

from ..core import serial
from ..core.behaviour import EffectOp, PrepareOp, registry
from ..core.clock import ClockContext

_SPLIT = re.compile(r"[\n ]")


def tokenize(doc: str) -> list:
    """Erlang binary:split on "\\n" and " " with [global]: keeps empties."""
    return _SPLIT.split(doc)


class _WordcountBase:
    #: dedupe tokens per document before counting (worddocumentcount)
    per_document: bool = False

    def new(self) -> Dict[str, int]:
        return {}

    def value(self, state: Dict[str, int]) -> Dict[str, int]:
        return dict(state)

    def downstream(
        self, op: PrepareOp, state: Any, ctx: ClockContext
    ) -> Optional[EffectOp]:
        kind, payload = op
        assert kind == "add"
        return ("add", payload)

    def update(self, effect: EffectOp, state: Dict[str, int]) -> Tuple[Any, list]:
        kind, payload = effect
        out = dict(state)
        if kind == "add":
            tokens = tokenize(payload)
            if self.per_document:
                tokens = set(tokens)
            for w in tokens:
                out[w] = out.get(w, 0) + 1
            return out, []
        if kind == "add_counts":
            for w, c in payload.items():
                out[w] = out.get(w, 0) + c
            return out, []
        raise ValueError(f"unsupported effect {effect!r}")

    def require_state_downstream(self, op: PrepareOp) -> bool:
        return False

    def is_operation(self, op: Any) -> bool:
        return (
            isinstance(op, tuple)
            and len(op) == 2
            and op[0] == "add"
            and isinstance(op[1], str)
        )

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        return False

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        return e1[0] in ("add", "add_counts") and e2[0] in ("add", "add_counts")

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        """Fuse both ops' counts (quirk #3 fix — never drop data)."""
        merged: Dict[str, int] = {}
        for e in (e1, e2):
            merged, _ = self.update(e, merged)
        return None, ("add_counts", merged)

    def equal(self, a: Any, b: Any) -> bool:
        return a == b

    def to_binary(self, state: Any) -> bytes:
        return serial.dumps_scalar(self.type_name, state)

    def from_binary(self, data: bytes) -> Any:
        name, state = serial.loads_scalar(data)
        assert name == self.type_name
        return state


class WordcountScalar(_WordcountBase):
    type_name = "wordcount"
    per_document = False


class WordDocumentCountScalar(_WordcountBase):
    type_name = "worddocumentcount"
    per_document = True


registry.register("wordcount", scalar=WordcountScalar())
registry.register("worddocumentcount", scalar=WordDocumentCountScalar())


# --- dense (TPU) level ----------------------------------------------------

import dataclasses  # noqa: E402
import functools  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from ..core.behaviour import MergeKind  # noqa: E402


class VocabEncoder:
    """Exact token -> dense id mapping (host-side), grown on demand.

    Tokenization happens on the host (the reference also does the split in
    the update itself, wordcount.erl:76-85); the device only ever sees
    integer token ids. For the ragged/unbounded-vocab benchmark config use
    `hash_token` instead — collisions then conflate words, the standard
    hashed-vocabulary trade."""

    def __init__(self):
        self.vocab: Dict[str, int] = {}

    def encode(self, doc: str, per_document: bool = False) -> list:
        tokens = tokenize(doc)
        if per_document:
            # worddocumentcount: <=1 contribution per word per document
            # (worddocumentcount.erl:76-86).
            tokens = sorted(set(tokens))
        out = []
        for t in tokens:
            if t not in self.vocab:
                self.vocab[t] = len(self.vocab)
            out.append(self.vocab[t])
        return out

    def decode_counts(self, counts) -> Dict[str, int]:
        inv = {i: t for t, i in self.vocab.items()}
        return {
            inv[i]: int(c) for i, c in enumerate(counts) if int(c) != 0 and i in inv
        }


def hash_token(token: str, n_buckets: int) -> int:
    """FNV-1a 32-bit, stable across runs/processes (unlike Python's hash)."""
    h = 2166136261
    for b in token.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % n_buckets


class HashedVocab:
    """Hashed-vocabulary encoder WITH collision accounting (round-2; the
    round-1 hashed path silently conflated colliding words' counts with no
    observability — VERDICT r1 weak #7 / next-step #9).

    Mechanism: first-seen token per bucket; a different token hashing to
    an owned bucket flags the bucket collided, and every op landing on a
    flagged bucket (the owner's included) counts as conflated —
    `lost`-style observability (cf. WordcountDenseState.lost) for the
    exactness loss the hashed table otherwise hides. Ops the owner issued
    BEFORE the bucket was flagged are not retroactively counted
    (streaming accounting); the per-bucket decoded count is the true
    conflated mass once flagged. Host-side by design: the encoder is the
    only place exact string identity exists (the device sees integer
    buckets; reference semantics are exact counts, wordcount.erl:76-85),
    and keeping the planes out of the replicated state keeps the MONOID
    delta algebra (`parallel/delta.py`) untouched.

    SCOPE: accounting is per encoder. In a multi-replica deployment where
    each ingest pipeline has its own HashedVocab, a cross-replica
    collision (replica 1 feeds word A, replica 2 feeds word B, same
    bucket) is invisible to either side alone — `merge` the encoders
    (alongside the count-state merge) before trusting `report`/
    `decode_counts`; `decode_counts` reports counts in buckets this
    encoder never saw under an explicit `<unattributed ...>` key rather
    than dropping or misattributing them.

    Counts in collided buckets are sums over the listed words — still
    deterministic and convergent, just coarser than the reference; every
    other bucket is exact.
    """

    def __init__(self, n_buckets: int):
        self.V = n_buckets
        self._owner: Dict[int, str] = {}
        self.collided: Dict[int, list] = {}  # bucket -> [owner, others...]
        self.conflated_ops = 0  # ops landing on a bucket after it was flagged

    def encode_token(self, token: str) -> int:
        b = hash_token(token, self.V)
        own = self._owner.get(b)
        if own is None:
            self._owner[b] = token
        elif own != token:
            members = self.collided.setdefault(b, [own])
            if token not in members:
                members.append(token)
        if b in self.collided:
            self.conflated_ops += 1
        return b

    def encode(self, doc: str, per_document: bool = False) -> list:
        tokens = tokenize(doc)
        if per_document:
            tokens = sorted(set(tokens))
        return [self.encode_token(t) for t in tokens]

    def merge(self, other: "HashedVocab") -> None:
        """Union another encoder's ownership/collision knowledge into this
        one — the encoder-side counterpart of the count-state merge. A
        bucket owned by different words on the two sides becomes collided
        here (the cross-replica collision neither side could see)."""
        if other.V != self.V:
            raise ValueError(f"bucket-count mismatch: {self.V} vs {other.V}")
        for b, tok in other._owner.items():
            own = self._owner.get(b)
            if own is None:
                self._owner[b] = tok
            elif own != tok:
                members = self.collided.setdefault(b, [own])
                if tok not in members:
                    members.append(tok)
        for b, ws in other.collided.items():
            members = self.collided.setdefault(b, [self._owner[b]])
            for w in ws:
                if w not in members:
                    members.append(w)
        self.conflated_ops += other.conflated_ops

    def report(self) -> Dict[str, Any]:
        return {
            "n_buckets": self.V,
            "buckets_owned": len(self._owner),
            "buckets_collided": len(self.collided),
            "conflated_ops": self.conflated_ops,
            "collided_words": {b: list(ws) for b, ws in self.collided.items()},
        }

    def decode_counts(self, counts) -> Dict[Any, int]:
        """bucket counts -> {word: count}. A collided bucket's count is
        reported under a tuple of ALL its words (explicitly conflated, no
        silent winner); a nonzero bucket this encoder never fed is
        reported under an explicit unattributed key (it came from another
        pipeline — merge the encoders for attribution)."""
        out: Dict[Any, int] = {}
        for b, c in enumerate(counts):
            c = int(c)
            if c == 0:
                continue
            if b in self.collided:
                out[tuple(self.collided[b])] = c
            elif b in self._owner:
                out[self._owner[b]] = c
            else:
                out[f"<unattributed bucket {b}>"] = c
        return out


def vocab_collision_audit(words, n_buckets: int) -> Dict[str, Any]:
    """Exact collision census of a vocabulary under FNV-1a % n_buckets
    (vectorized via harness.native_tokenizer.fnv1a_buckets): the measured
    collision-rate artifact for a deployment's (vocab, V) choice, e.g.
    BASELINE's ragged-vocab configs."""
    import numpy as np

    from ..harness.native_tokenizer import fnv1a_buckets

    words = list(dict.fromkeys(words))
    buckets = fnv1a_buckets(words, n_buckets)
    _, counts = np.unique(buckets, return_counts=True)
    n_collided_buckets = int((counts > 1).sum())
    words_in_collided = int(counts[counts > 1].sum())
    return {
        "n_words": len(words),
        "n_buckets": n_buckets,
        "buckets_collided": n_collided_buckets,
        "words_in_collided_buckets": words_in_collided,
        "word_collision_rate": words_in_collided / max(1, len(words)),
    }


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class WordcountDenseState:
    counts: jax.Array  # i32[R, NK, V]
    lost: jax.Array  # i32[R, NK] — tokens dropped because id >= V


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class WordcountOps:
    """Token-id batch per replica; token < 0 marks padding."""

    key: jax.Array  # i32[R, B]
    token: jax.Array  # i32[R, B]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class WordDocOps:
    """Raw per-token records for device-side per-document dedup
    (`apply_doc_ops`); token < 0 marks padding. A document's records must
    not split across batches (dedup is per batch).

    `uniq` is the dedup identity and `token` the count target. They
    differ in hashed-vocabulary mode: dedup must be on *string* identity
    (worddocumentcount.erl:76-86 — two distinct words that hash-collide
    still contribute 2 to the shared bucket), so `uniq` carries the
    exact-vocabulary id and `token` the hashed bucket. In exact mode they
    are the same array."""

    key: jax.Array  # i32[R, B]
    doc: jax.Array  # i32[R, B]
    uniq: jax.Array  # i32[R, B]  dedup identity (exact-vocab id)
    token: jax.Array  # i32[R, B]  count target (bucket or exact id)


class WordcountDense:
    """Both wordcount variants share this kernel: the per-document dedup of
    worddocumentcount is an encode-time concern (VocabEncoder per_document).
    Counts form a commutative monoid, so per-replica states are deltas and
    merge is + (MONOID; cf. MergeKind)."""

    type_name = "wordcount"
    merge_kind = MergeKind.MONOID

    def __init__(self, n_buckets: int):
        self.V = n_buckets

    def init(self, n_replicas: int, n_keys: int = 1) -> WordcountDenseState:
        return WordcountDenseState(
            counts=jnp.zeros((n_replicas, n_keys, self.V), jnp.int32),
            lost=jnp.zeros((n_replicas, n_keys), jnp.int32),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def apply_ops(self, state: WordcountDenseState, ops: WordcountOps):
        NK, V = state.counts.shape[1], self.V

        def per_replica(counts, lost, key, token):
            k = jnp.where(token >= 0, key, NK)  # padding -> dropped
            counts = counts.at[k, token].add(1, mode="drop")
            # Token ids beyond the table are dropped by the scatter; record
            # them so exactness loss is visible (cf. topk_rmv's lossy flag).
            over = jnp.where(token >= V, k, NK)
            lost = lost.at[over].add(1, mode="drop")
            return counts, lost

        counts, lost = jax.vmap(per_replica)(
            state.counts, state.lost, ops.key, ops.token
        )
        return WordcountDenseState(counts, lost), None

    @functools.partial(jax.jit, static_argnums=0)
    def apply_doc_ops(self, state: WordcountDenseState, ops: "WordDocOps"):
        """worddocumentcount ingest with the per-document dedup ON DEVICE
        (worddocumentcount.erl:76-86 semantics): raw per-token records
        stream in un-deduped; a sort by (key, doc, uniq) makes duplicates
        adjacent, only run heads count, and the head's `token` (the
        hashed bucket in hashed-vocab mode) receives the count. Dedup on
        `uniq` — string identity — keeps hash-collision semantics equal
        to the scalar/host paths. Moves the dedup off the host — this box
        has one CPU, the tokenizer need only split and id — onto the TPU
        where it is one 4-operand sort over the batch."""
        NK = state.counts.shape[1]

        def per_replica(counts, lost, key, doc, uniq, token):
            k = jnp.where(token >= 0, key, NK)
            ks, ds, us, ts = lax.sort((k, doc, uniq, token), num_keys=3)
            dup = (
                (ks == jnp.roll(ks, 1))
                & (ds == jnp.roll(ds, 1))
                & (us == jnp.roll(us, 1))
            )
            dup = dup.at[0].set(False)
            ks = jnp.where(dup, NK, ks)  # only run heads count
            counts = counts.at[ks, ts].add(1, mode="drop")
            over = jnp.where(ts >= self.V, ks, NK)
            lost = lost.at[over].add(1, mode="drop")
            return counts, lost

        counts, lost = jax.vmap(per_replica)(
            state.counts, state.lost, ops.key, ops.doc, ops.uniq, ops.token
        )
        return WordcountDenseState(counts, lost), None

    @functools.partial(jax.jit, static_argnums=0)
    def apply_doc_ops_compact(
        self,
        state: WordcountDenseState,
        uniq: jax.Array,
        doc_lens: jax.Array,
        counts: jax.Array,
        bucket_table: Optional[jax.Array] = None,
        key: jax.Array | int = 0,
    ):
        """`apply_doc_ops` fed by the COMPACT ingest wire (VERDICT-r3 item
        6): of the three [R, B] planes the raw wire carries, two are pure
        redundancy — `doc` is the run-length expansion of per-document
        token counts, and `token` is a function of `uniq` (the exact-id ->
        bucket map, one FNV pass over the vocabulary). So the wire ships
        only `uniq` + `doc_lens` [R, DOCS] + per-replica live `counts`,
        and this wrapper rebuilds the planes device-side:

        * doc — positions are document-major, so doc[p] is a searchsorted
          of p against the cumulative lengths (empty documents own no
          positions and are skipped by side='right').
        * token — one gather from the resident `bucket_table` (uploaded
          once per corpus like model weights; ~2 bytes/vocab-word vs
          2 bytes/TOKEN for the full plane). `None` = exact mode
          (token == uniq), matching WordDocOps' exact-mode convention.

        Dedup semantics are unchanged — the rebuilt planes feed the same
        sort kernel, and `uniq` (string identity) remains the dedup key
        (worddocumentcount.erl:76-86). Padding beyond counts[r] is
        remapped to token=-1 exactly like the raw wire's sentinel.
        `key` (scalar) targets one NK row like the raw builder's key
        plane; a compact batch addresses a single key — batches spanning
        keys must use the raw WordDocOps wire."""
        B = uniq.shape[1]
        pos = jnp.arange(B, dtype=jnp.int32)
        live = pos[None, :] < counts[:, None]
        uniq32 = jnp.where(live, uniq.astype(jnp.int32), -1)
        cum = jnp.cumsum(doc_lens.astype(jnp.int32), axis=-1)
        doc = jax.vmap(
            lambda c: jnp.searchsorted(c, pos, side="right")
        )(cum).astype(jnp.int32)
        if bucket_table is None:
            token = uniq32
        else:
            tbl = bucket_table.astype(jnp.int32)
            token = jnp.take(tbl, jnp.clip(uniq32, 0, tbl.shape[0] - 1))
            # A live uniq id outside the resident table (negative or past
            # the end) has no bucket; the raw wire could never produce it,
            # so route it to an out-of-range token that lands in `lost`
            # rather than clamping it into an arbitrary table entry (a
            # silent miscount). Dead entries get -1 below regardless.
            token = jnp.where(
                (uniq32 >= tbl.shape[0]) | (uniq32 < 0),
                jnp.int32(self.V), token,
            )
            token = jnp.where(live, token, -1)
        ops = WordDocOps(
            key=jnp.full_like(uniq32, key), doc=doc, uniq=uniq32, token=token
        )
        return self.apply_doc_ops(state, ops)

    @functools.partial(jax.jit, static_argnums=0)
    def merge(self, a: WordcountDenseState, b: WordcountDenseState):
        return WordcountDenseState(a.counts + b.counts, a.lost + b.lost)

    def observe(self, state: WordcountDenseState):
        return state.counts

    def equal(self, a, b) -> bool:
        return bool(jnp.all(a.counts == b.counts))


def make_dense(n_buckets: int) -> WordcountDense:
    return WordcountDense(n_buckets=n_buckets)


# Both wordcount variants share the dense kernel; the per-document dedup of
# worddocumentcount happens at encode time (VocabEncoder per_document).
registry.register("wordcount", dense_factory=make_dense)
registry.register("worddocumentcount", dense_factory=make_dense)
