"""wordcount / worddocumentcount: grow-only word -> count maps.

Reference: ``src/antidote_ccrdt_wordcount.erl`` and
``src/antidote_ccrdt_worddocumentcount.erl``. An ``add`` carries a document
(a string); the update splits it on ``"\\n"`` / ``" "`` and folds counts
(``wordcount.erl:76-85``). ``worddocumentcount`` dedupes words within the
document first (through a gb_set, ``worddocumentcount.erl:76-86``) so each
document contributes at most 1 per word. Downstream is stateless
(``wordcount.erl:50-51``).

Tokenization parity note: Erlang's ``binary:split(_, _, [global])`` keeps
empty segments, so consecutive separators yield empty-string "words" that
the reference counts. We reproduce that exactly (``re.split``).

Deliberate fix (SURVEY.md §2 quirk #3): the reference's ``compact_ops``
returns ``{noop, noop}`` — *discarding both ops* and silently losing data if
the host compacts (``wordcount.erl:70-72``). Word counts form a trivial
commutative monoid, so here compaction fuses the two ops into one
``add_counts`` op carrying the combined counts.

Dense design (SURVEY.md §7): hashed-vocabulary count table ``i32[R, V]``;
documents are tokenized host-side into hash ids, an op batch is one
bincount/segment-sum, and the cross-replica merge is ``+`` (MONOID).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

from ..core import serial
from ..core.behaviour import EffectOp, PrepareOp, registry
from ..core.clock import ReplicaContext

_SPLIT = re.compile(r"[\n ]")


def tokenize(doc: str) -> list:
    """Erlang binary:split on "\\n" and " " with [global]: keeps empties."""
    return _SPLIT.split(doc)


class _WordcountBase:
    #: dedupe tokens per document before counting (worddocumentcount)
    per_document: bool = False

    def new(self) -> Dict[str, int]:
        return {}

    def value(self, state: Dict[str, int]) -> Dict[str, int]:
        return dict(state)

    def downstream(
        self, op: PrepareOp, state: Any, ctx: ReplicaContext
    ) -> Optional[EffectOp]:
        kind, payload = op
        assert kind == "add"
        return ("add", payload)

    def update(self, effect: EffectOp, state: Dict[str, int]) -> Tuple[Any, list]:
        kind, payload = effect
        out = dict(state)
        if kind == "add":
            tokens = tokenize(payload)
            if self.per_document:
                tokens = set(tokens)
            for w in tokens:
                out[w] = out.get(w, 0) + 1
            return out, []
        if kind == "add_counts":
            for w, c in payload.items():
                out[w] = out.get(w, 0) + c
            return out, []
        raise ValueError(f"unsupported effect {effect!r}")

    def require_state_downstream(self, op: PrepareOp) -> bool:
        return False

    def is_operation(self, op: Any) -> bool:
        return (
            isinstance(op, tuple)
            and len(op) == 2
            and op[0] == "add"
            and isinstance(op[1], str)
        )

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        return False

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        return e1[0] in ("add", "add_counts") and e2[0] in ("add", "add_counts")

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        """Fuse both ops' counts (quirk #3 fix — never drop data)."""
        merged: Dict[str, int] = {}
        for e in (e1, e2):
            merged, _ = self.update(e, merged)
        return None, ("add_counts", merged)

    def equal(self, a: Any, b: Any) -> bool:
        return a == b

    def to_binary(self, state: Any) -> bytes:
        return serial.dumps_scalar(self.type_name, state)

    def from_binary(self, data: bytes) -> Any:
        name, state = serial.loads_scalar(data)
        assert name == self.type_name
        return state


class WordcountScalar(_WordcountBase):
    type_name = "wordcount"
    per_document = False


class WordDocumentCountScalar(_WordcountBase):
    type_name = "worddocumentcount"
    per_document = True


registry.register("wordcount", scalar=WordcountScalar())
registry.register("worddocumentcount", scalar=WordDocumentCountScalar())
