"""leaderboard: top-K with permanent player bans.

Reference: ``src/antidote_ccrdt_leaderboard.erl``. Unlike topk_rmv's
add-wins removal, a ban is irreversible (``:21-27``), so no causal metadata
is needed: the 5-tuple state ``{Observed, Masked, Bans, Min, Size}``
(``:62-68``) keeps only the best score per player, a ban set, and a cached
min. ``Masked`` holds the best score of each non-observed player so a ban
of an observed player can promote a replacement (``:265-286``), emitting an
extra ``("add", promoted)`` op (``:279-283``).

Dense design (SURVEY.md §7): per (replica, key) a direct-indexed player
table — ``best_score[P]``, ``seen[P]``, ``banned[P]`` — where applying an
op batch is a segment-max scatter and the cross-replica merge is
elementwise ``max`` / ``or`` (JOIN algebra). Observed/masked/min are
*derived* views (masked top-K), not materialized: recomputing them
vectorized replaces the reference's incremental min/promotion bookkeeping
(the hot paths at ``leaderboard.erl:298-312``).
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, NamedTuple, Optional, Tuple

from ..core import serial
from ..core.behaviour import EffectOp, PrepareOp, registry
from ..core.clock import ClockContext

Pair = Tuple[Any, Any]  # (id, score); (None, None) is the reference's {nil, nil}
NIL: Pair = (None, None)


class LeaderboardState(NamedTuple):
    observed: Dict[Any, int]
    masked: Dict[Any, int]
    bans: FrozenSet[Any]
    min: Pair
    size: int


def _cmp(a: Pair, b: Pair) -> bool:
    """Strict 'a beats b': score then id (leaderboard.erl:289-294)."""
    if a == NIL:
        return False
    if b == NIL:
        return True
    i1, s1 = a
    i2, s2 = b
    return s1 > s2 or (s1 == s2 and i1 > i2)


def _min_pair(observed: Dict[Any, int]) -> Pair:
    """Smallest (id, score) by cmp order (leaderboard.erl:297-303)."""
    best = NIL
    for pair in observed.items():
        if best == NIL or _cmp(best, pair):
            best = pair
    return best


def _largest(masked: Dict[Any, int]) -> Pair:
    """Largest (id, score) by cmp order (leaderboard.erl:306-312)."""
    best = NIL
    for pair in masked.items():
        if best == NIL or _cmp(pair, best):
            best = pair
    return best


class LeaderboardScalar:
    type_name = "leaderboard"

    def new(self, size: int = 100) -> LeaderboardState:
        assert isinstance(size, int) and size > 0
        return LeaderboardState({}, {}, frozenset(), NIL, size)

    def value(self, state: LeaderboardState) -> list:
        return sorted(state.observed.items())

    def downstream(
        self, op: PrepareOp, state: LeaderboardState, ctx: ClockContext
    ) -> Optional[EffectOp]:
        """leaderboard.erl:94-116 filter cascade."""
        kind, payload = op
        if kind == "add":
            id_, score = payload
            if id_ in state.bans:
                return None
            if id_ in state.observed:
                return ("add", (id_, score)) if score > state.observed[id_] else None
            if id_ in state.masked and score <= state.masked[id_]:
                return None
            if len(state.observed) < state.size or _cmp((id_, score), state.min):
                return ("add", (id_, score))
            return ("add_r", (id_, score))
        if kind == "ban":
            id_ = payload
            return None if id_ in state.bans else ("ban", id_)
        raise ValueError(f"unsupported op {op!r}")

    def update(
        self, effect: EffectOp, state: LeaderboardState
    ) -> Tuple[LeaderboardState, list]:
        kind, payload = effect
        if kind in ("add", "add_r"):
            return self._add(payload[0], payload[1], state)
        if kind == "ban":
            return self._ban(payload, state)
        raise ValueError(f"unsupported effect {effect!r}")

    def _add(self, id_, score, state: LeaderboardState):
        """leaderboard.erl:216-261."""
        if id_ in state.bans:
            return state, []
        if id_ in state.observed:
            if score > state.observed[id_]:
                new_obs = dict(state.observed)
                new_obs[id_] = score
                new_min = _min_pair(new_obs) if state.min[0] == id_ else state.min
                return state._replace(observed=new_obs, min=new_min), []
            return state, []
        if len(state.observed) == state.size:
            if _cmp((id_, score), state.min):
                # Promote over the min: min is demoted to masked (:237-242).
                min_id, min_score = state.min
                masked = dict(state.masked)
                masked.pop(id_, None)
                new_obs = dict(state.observed)
                new_obs[id_] = score
                del new_obs[min_id]
                masked[min_id] = min_score
                return (
                    state._replace(
                        observed=new_obs, masked=masked, min=_min_pair(new_obs)
                    ),
                    [],
                )
            if id_ not in state.masked or score > state.masked[id_]:
                masked = dict(state.masked)
                masked[id_] = score
                return state._replace(masked=masked), []
            return state, []
        new_obs = dict(state.observed)
        new_obs[id_] = score
        new_min = (
            (id_, score)
            if state.min == NIL or _cmp(state.min, (id_, score))
            else state.min
        )
        return state._replace(observed=new_obs, min=new_min), []

    def _ban(self, id_, state: LeaderboardState):
        """leaderboard.erl:265-286."""
        masked1 = dict(state.masked)
        masked1.pop(id_, None)
        obs1 = dict(state.observed)
        was_observed = id_ in obs1
        obs1.pop(id_, None)
        bans1 = state.bans | {id_}
        if not was_observed:
            return state._replace(masked=masked1, bans=bans1), []
        new_elem = _largest(state.masked)  # pre-ban masked, as in :271
        if new_elem == NIL:
            new_min = _min_pair(obs1) if state.min[0] == id_ else state.min
            return (
                LeaderboardState(obs1, masked1, bans1, new_min, state.size),
                [],
            )
        new_id, new_score = new_elem
        masked2 = dict(masked1)
        masked2.pop(new_id, None)
        obs2 = dict(obs1)
        obs2[new_id] = new_score
        new_state = LeaderboardState(obs2, masked2, bans1, new_elem, state.size)
        return new_state, [("add", new_elem)]

    def require_state_downstream(self, op: PrepareOp) -> bool:
        return True

    def is_operation(self, op: Any) -> bool:
        if not (isinstance(op, tuple) and len(op) == 2):
            return False
        kind, payload = op
        if kind == "add":
            return (
                isinstance(payload, tuple)
                and len(payload) == 2
                and all(isinstance(x, int) for x in payload)
            )
        if kind == "ban":
            return isinstance(payload, int)
        return False

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        return effect[0] == "add_r"

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        """leaderboard.erl:163-174."""
        k1, k2 = e1[0], e2[0]
        if k1 in ("add", "add_r") and k2 in ("add", "add_r"):
            return e1[1][0] == e2[1][0]
        if k1 in ("add", "add_r") and k2 == "ban":
            return e1[1][0] == e2[1]
        if (k1, k2) == ("ban", "ban"):
            return e1[1] == e2[1]
        return False

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        """leaderboard.erl:177-205. None marks the dead slot."""
        k1, k2 = e1[0], e2[0]
        if k1 in ("add", "add_r") and k2 in ("add", "add_r"):
            if e1[1][1] > e2[1][1]:
                return e1, None
            return None, e2
        if k1 in ("add", "add_r") and k2 == "ban":
            return None, e2
        if (k1, k2) == ("ban", "ban"):
            return None, e2
        raise ValueError(f"cannot compact {e1!r}, {e2!r}")

    def equal(self, a: LeaderboardState, b: LeaderboardState) -> bool:
        # Observable state only (leaderboard.erl:137-139).
        return a.observed == b.observed and a.size == b.size

    def to_binary(self, state: LeaderboardState) -> bytes:
        return serial.dumps_scalar(self.type_name, tuple(state))

    def from_binary(self, data: bytes) -> LeaderboardState:
        name, payload = serial.loads_scalar(data)
        assert name == self.type_name
        obs, masked, bans, min_, size = payload
        return LeaderboardState(obs, masked, frozenset(bans), tuple(min_), size)


registry.register(
    "leaderboard", scalar=LeaderboardScalar(), generates_extra_operations=True
)


# --- dense (TPU) level ----------------------------------------------------

import dataclasses  # noqa: E402
import functools  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ..core.behaviour import MergeKind  # noqa: E402
from ..ops.dense_table import (  # noqa: E402
    NEG_INF,
    masked_topk,
    observables_equal,
    observe_value,
    promotion_mask,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LeaderboardDenseState:
    """Direct-indexed player table per (replica, instance): the best known
    score per player and a permanent ban mask. The reference's
    observed/masked/min split (leaderboard.erl:62-68) is an incremental-
    computation artifact; the underlying lattice is exactly (per-player max
    score, ban set), with the observable top-K *derived* — which makes the
    cross-replica merge pure elementwise max/or."""

    best_score: jax.Array  # i32[R, NK, P]; NEG_INF = never seen
    banned: jax.Array  # bool[R, NK, P]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LeaderboardOps:
    """Effect-op batch per replica. add_valid/ban_valid mask padding."""

    add_key: jax.Array  # i32[R, B]
    add_id: jax.Array  # i32[R, B]
    add_score: jax.Array  # i32[R, B]
    add_valid: jax.Array  # bool[R, B]
    ban_key: jax.Array  # i32[R, Bb]
    ban_id: jax.Array  # i32[R, Bb]
    ban_valid: jax.Array  # bool[R, Bb]


class LeaderboardDense:
    """Batched leaderboard over [n_replicas, n_keys]; P = player-id space,
    K = board size. Cites: ban permanence (leaderboard.erl:21-27), ban wins
    over any add (add_after_ban_test :494-499)."""

    type_name = "leaderboard"
    merge_kind = MergeKind.JOIN

    def __init__(self, n_players: int, size: int = 100):
        self.P = n_players
        self.K = size

    def init(self, n_replicas: int, n_keys: int = 1) -> LeaderboardDenseState:
        shape = (n_replicas, n_keys, self.P)
        return LeaderboardDenseState(
            best_score=jnp.full(shape, NEG_INF, dtype=jnp.int32),
            banned=jnp.zeros(shape, dtype=bool),
        )

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def apply_ops(
        self,
        state: LeaderboardDenseState,
        ops: LeaderboardOps,
        collect_promotions: bool = False,
    ):
        old_obs = self.observe(state) if collect_promotions else None
        NK = state.best_score.shape[1]

        def per_replica(score, banned, o: LeaderboardOps):
            ak = jnp.where(o.add_valid, o.add_key, NK)  # OOB -> dropped
            score = score.at[ak, o.add_id].max(o.add_score, mode="drop")
            bk = jnp.where(o.ban_valid, o.ban_key, NK)
            banned = banned.at[bk, o.ban_id].set(True, mode="drop")
            return score, banned

        score, banned = jax.vmap(per_replica)(state.best_score, state.banned, ops)
        new_state = LeaderboardDenseState(score, banned)
        promoted = None
        if collect_promotions:
            promoted = self._promotions(old_obs, self.observe(new_state), ops)
        return new_state, promoted

    @functools.partial(jax.jit, static_argnums=0)
    def merge(self, a: LeaderboardDenseState, b: LeaderboardDenseState):
        return LeaderboardDenseState(
            best_score=jnp.maximum(a.best_score, b.best_score),
            banned=a.banned | b.banned,
        )

    @functools.partial(jax.jit, static_argnums=0)
    def observe(self, state: LeaderboardDenseState):
        """(ids, scores, valid) of the top-K non-banned players, score desc
        with id-desc tiebreak (leaderboard cmp, :289-294)."""
        return masked_topk(
            jnp.where(state.banned, NEG_INF, state.best_score), self.K
        )

    def value(self, state: LeaderboardDenseState):
        return observe_value(self.observe, state)

    def equal(self, a, b) -> bool:
        return observables_equal(self.observe(a), self.observe(b))

    def _promotions(self, old, new, ops: LeaderboardOps):
        """Entries of the new observable absent from both the old observable
        and this batch's adds *to the same instance* — i.e. uncovered by
        bans (leaderboard.erl:279-283); identity is (id, score) since adds
        carry no timestamps."""
        old_ids, old_scores, old_valid = old
        new_ids, new_scores, new_valid = new
        keep = promotion_mask(
            (new_ids, new_scores),
            new_valid,
            (old_ids, old_scores),
            old_valid,
            ops.add_key,
            (ops.add_id, ops.add_score),
            ops.add_valid,
        )
        return new_ids, new_scores, keep


def make_dense(n_players: int, size: int = 100) -> LeaderboardDense:
    return LeaderboardDense(n_players=n_players, size=size)


registry.register("leaderboard", dense_factory=make_dense)
