"""average: aggregated mean as a (sum, count) pair.

Reference: ``src/antidote_ccrdt_average.erl``. State is ``{Sum, N}``
(``:57-58``); adds carry either a bare value or a partial ``{Sum, N}``
(``:78-81``); downstream is stateless (``:132``); two adds compact into one
(``:127``). One deliberate fix (SURVEY.md §2 quirk #2): ``value/1`` on a
fresh state divides by zero in the reference (``average.erl:69-70``) — here
it returns 0.0.

Dense design (SURVEY.md §7): state is ``int64[R, K, 2]`` (sum, n) over
[n_replicas, n_keys]; applying an op batch is one ``segment_sum`` per
replica, and the cross-replica merge is elementwise ``+`` (MONOID algebra:
per-replica states are deltas — see `MergeKind`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import serial
from ..core.behaviour import EffectOp, MergeKind, PrepareOp, registry
from ..core.clock import ClockContext


class AverageScalar:
    type_name = "average"

    def new(self, sum_: int = 0, num: int = 0) -> Tuple[int, int]:
        return (int(sum_), int(num))

    def value(self, state: Tuple[int, int]) -> float:
        s, n = state
        if n == 0:
            return 0.0
        return s / n

    def downstream(
        self, op: PrepareOp, state: Any, ctx: ClockContext
    ) -> Optional[EffectOp]:
        kind, payload = op
        assert kind == "add"
        if isinstance(payload, tuple):
            v, n = payload
            return ("add", (int(v), int(n)))
        return ("add", (int(payload), 1))

    def update(self, effect: EffectOp, state: Tuple[int, int]) -> Tuple[Any, list]:
        kind, payload = effect
        assert kind == "add"
        if isinstance(payload, tuple):
            v, n = payload
        else:
            v, n = int(payload), 1
        if n == 0:  # reference no-op guard, average.erl:89
            return state, []
        s, cn = state
        return (s + v, cn + n), []

    def require_state_downstream(self, op: PrepareOp) -> bool:
        return False

    def is_operation(self, op: Any) -> bool:
        if not (isinstance(op, tuple) and len(op) == 2 and op[0] == "add"):
            return False
        p = op[1]
        if isinstance(p, tuple):
            return len(p) == 2 and all(isinstance(x, int) for x in p)
        return isinstance(p, int)

    @staticmethod
    def _fuse(e1: EffectOp, e2: EffectOp):
        # An n=0 op is a no-op in update (the `average.erl:89` guard), so it
        # must contribute nothing when fused either — the reference fuses
        # blindly (`average.erl:127`), silently resurrecting the dead op's
        # sum; deliberate fix, caught by test_compaction_preserves_state_average.
        (v1, n1), (v2, n2) = e1[1], e2[1]
        if n1 == 0:
            v1 = 0
        if n2 == 0:
            v2 = 0
        return v1 + v2, n1 + n2

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        if e1[0] != "add" or e2[0] != "add":
            return False
        # Refuse fusions whose combined n is 0 while the combined sum is
        # not: the fused op would hit the n=0 update guard and drop the
        # sum that sequential application keeps (possible because
        # is_operation admits negative n).
        v, n = self._fuse(e1, e2)
        return n != 0 or v == 0

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        v, n = self._fuse(e1, e2)
        return None, ("add", (v, n))

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        return False

    def equal(self, a: Any, b: Any) -> bool:
        return a == b

    def to_binary(self, state: Any) -> bytes:
        return serial.dumps_scalar(self.type_name, state)

    def from_binary(self, data: bytes) -> Any:
        name, state = serial.loads_scalar(data)
        assert name == self.type_name
        return state


# --- dense (TPU) level ----------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AverageState:
    """sum/n accumulators, shape [n_replicas, n_keys]."""

    sum: jax.Array
    num: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AverageOps:
    """A batch of add ops per replica: op b on replica r targets key[r, b]
    adding (value[r, b], count[r, b]). count==0 marks padding (the
    reference's own no-op guard makes 0 the natural null)."""

    key: jax.Array  # int32[R, B]
    value: jax.Array  # [R, B], state dtype
    count: jax.Array  # [R, B], state dtype


class AverageDense:
    """dtype defaults to int32: TPUs emulate int64 (pairs of i32 registers,
    2x HBM traffic), and the harness's logical clocks / bench workloads fit
    i32 comfortably. Pass int64 where real wall-clock sums demand it."""

    type_name = "average"
    merge_kind = MergeKind.MONOID

    def __init__(self, dtype=jnp.int32):
        self.dtype = dtype

    def init(self, n_replicas: int, n_keys: int) -> AverageState:
        z = jnp.zeros((n_replicas, n_keys), dtype=self.dtype)
        return AverageState(sum=z, num=z)

    def apply_ops(self, state: AverageState, ops: AverageOps):
        # count==0 ops are no-ops end to end (average.erl:89): their value
        # must not leak into the sum either.
        value = jnp.where(ops.count == 0, 0, ops.value)

        def per_replica(s, n, key, value, count):
            s = s.at[key].add(value, mode="drop")
            n = n.at[key].add(count, mode="drop")
            return s, n

        new_sum, new_num = jax.vmap(per_replica)(
            state.sum, state.num, ops.key, value, ops.count
        )
        return AverageState(sum=new_sum, num=new_num), None

    def merge(self, a: AverageState, b: AverageState) -> AverageState:
        return AverageState(sum=a.sum + b.sum, num=a.num + b.num)

    def observe(self, state: AverageState) -> jax.Array:
        return jnp.where(state.num == 0, 0.0, state.sum / jnp.maximum(state.num, 1))


registry.register(
    "average",
    scalar=AverageScalar(),
    dense=AverageDense(),
    dense_factory=AverageDense,
)
