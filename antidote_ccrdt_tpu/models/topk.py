"""topk: bounded top-K of (id, score) pairs, per-id max.

Reference: ``src/antidote_ccrdt_topk.erl`` — but rebuilt, not ported:
SURVEY.md §2 quirk #1 documents that the reference's ``topk`` is actually a
*filtered grow-only map* (its "size" field is used as a score threshold in
``changes_state`` ``:164-166``, ``add`` never prunes ``:157-158``, and its
own ``new_test`` fails). Per the survey directive this rebuild implements a
real bounded top-K:

* state = at most K (id, score) entries, keeping the max score per id;
* ``downstream`` drops ops that cannot change the observable state
  (the reference's filtering concept, ``topk.erl:90-94``, done right);
* compaction batches adds into one ``add_map`` op (``:136-146``) but merges
  duplicate ids with **max** rather than the reference's order-dependent
  last-wins (quirk #4, ``topk.erl:160-161``).

The state is a join-semilattice (join = per-id max, then top-K by
(score, id) order), so the dense merge is JOIN algebra.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

from ..core import serial
from ..core.behaviour import EffectOp, PrepareOp, registry
from ..core.clock import ClockContext


class TopkState(NamedTuple):
    entries: Dict[Any, int]  # id -> best score; len <= size
    size: int


def _beats(a: Tuple[Any, int], b: Tuple[Any, int]) -> bool:
    """(id, score) strict order: score desc, then id desc (topk.erl:83)."""
    i1, s1 = a
    i2, s2 = b
    return s1 > s2 or (s1 == s2 and i1 > i2)


def _min_entry(entries: Dict[Any, int]) -> Optional[Tuple[Any, int]]:
    best = None
    for pair in entries.items():
        if best is None or _beats(best, pair):
            best = pair
    return best


def _join(entries: Dict[Any, int], items, size: int) -> Dict[Any, int]:
    """Per-id max over the union, then keep the top `size` by order."""
    merged = dict(entries)
    for id_, score in items:
        if id_ not in merged or score > merged[id_]:
            merged[id_] = score
    if len(merged) <= size:
        return merged
    ranked = sorted(merged.items(), key=lambda p: (p[1], p[0]), reverse=True)
    return dict(ranked[:size])


class TopkScalar:
    type_name = "topk"

    def new(self, size: int = 100) -> TopkState:
        assert isinstance(size, int) and size > 0
        return TopkState({}, size)

    def value(self, state: TopkState) -> list:
        return sorted(
            state.entries.items(), key=lambda p: (p[1], p[0]), reverse=True
        )

    def downstream(
        self, op: PrepareOp, state: TopkState, ctx: ClockContext
    ) -> Optional[EffectOp]:
        kind, payload = op
        assert kind == "add"
        id_, score = payload
        return ("add", (id_, score)) if self._changes_state(id_, score, state) else None

    def _changes_state(self, id_, score, state: TopkState) -> bool:
        if id_ in state.entries:
            return score > state.entries[id_]
        if len(state.entries) < state.size:
            return True
        min_ = _min_entry(state.entries)
        return _beats((id_, score), min_)

    def update(self, effect: EffectOp, state: TopkState) -> Tuple[TopkState, list]:
        kind, payload = effect
        if kind == "add":
            id_, score = payload
            return TopkState(_join(state.entries, [(id_, score)], state.size), state.size), []
        if kind == "add_map":
            return TopkState(_join(state.entries, payload.items(), state.size), state.size), []
        raise ValueError(f"unsupported effect {effect!r}")

    def require_state_downstream(self, op: PrepareOp) -> bool:
        return True

    def is_operation(self, op: Any) -> bool:
        return (
            isinstance(op, tuple)
            and len(op) == 2
            and op[0] == "add"
            and isinstance(op[1], tuple)
            and len(op[1]) == 2
            and isinstance(op[1][1], int)
        )

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        return False

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        return e1[0] in ("add", "add_map") and e2[0] in ("add", "add_map")

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        """Batch adds into one add_map; duplicate ids take max (quirk #4 fix)."""

        def items(e):
            return [e[1]] if e[0] == "add" else list(e[1].items())

        merged: Dict[Any, int] = {}
        for id_, score in items(e1) + items(e2):
            if id_ not in merged or score > merged[id_]:
                merged[id_] = score
        return None, ("add_map", merged)

    def equal(self, a: TopkState, b: TopkState) -> bool:
        return a.entries == b.entries and a.size == b.size

    def to_binary(self, state: TopkState) -> bytes:
        return serial.dumps_scalar(self.type_name, tuple(state))

    def from_binary(self, data: bytes) -> TopkState:
        name, payload = serial.loads_scalar(data)
        assert name == self.type_name
        entries, size = payload
        return TopkState(entries, size)


registry.register("topk", scalar=TopkScalar())


class TopkScalarCompat(TopkScalar):
    """Reference-OBSERVABLE topk semantics, quirks included, for
    differential testing against a live Antidote node.

    Decision record (VERDICT r1 missing #4): the rebuilt `TopkScalar`
    above is the product — a real bounded top-K per SURVEY §2 quirk #1's
    directive — and that decision is permanent. This class exists solely
    so the bridge can be driven against a host that runs the reference
    module and byte-level behavior must match. It reproduces, faithfully
    (`src/antidote_ccrdt_topk.erl`):

    * ``new()`` defaults to size **1000** (:65-66) even though the
      reference's own test expects 100;
    * ``downstream`` emits the add iff ``Score > Size`` — "size" is a
      score threshold, not a capacity (:164-166);
    * ``update`` add is ``maps:put`` — **last-wins**, not max (:157-158),
      and ``add`` never prunes: the state is a filtered grow-only map;
    * ``can_compact`` is always true and ``compact_ops`` merges duplicate
      ids last-wins via ``maps:merge`` (:136-146, :160-161) — an
      order-dependent result;
    * ``equal`` compares the full state (:107-109).

    NOT registered: `registry` whitelists the six reference type names and
    "topk" maps to the rebuilt engine. Construct this directly. Subclasses
    `TopkScalar`, overriding exactly the quirk-bearing callbacks; the rest
    (value ordering, serialization, equal, predicates) are shared.
    """

    type_name = "topk_compat"

    def new(self, size: int = 1000) -> TopkState:
        assert isinstance(size, int) and size > 0
        return TopkState({}, size)

    def downstream(
        self, op: PrepareOp, state: TopkState, ctx: ClockContext
    ) -> Optional[EffectOp]:
        kind, payload = op
        assert kind == "add"
        id_, score = payload
        # changes_state/2 (:164-166): Score > Size, nothing else.
        return ("add", (id_, score)) if score > state.size else None

    def update(self, effect: EffectOp, state: TopkState) -> Tuple[TopkState, list]:
        kind, payload = effect
        if kind == "add":
            id_, score = payload
            entries = dict(state.entries)
            entries[id_] = score  # maps:put — last-wins (:157-158)
            return TopkState(entries, state.size), []
        if kind == "add_map":
            entries = dict(state.entries)
            entries.update(payload)  # maps:merge — last-wins (:160-161)
            return TopkState(entries, state.size), []
        raise ValueError(f"unsupported effect {effect!r}")

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        return True  # (:131-132)

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        def items(e):
            return [e[1]] if e[0] == "add" else list(e[1].items())

        merged: Dict[Any, int] = {}
        for id_, score in items(e1) + items(e2):
            merged[id_] = score  # last-wins, in op order (:136-146)
        return None, ("add_map", merged)


# --- dense (TPU) level ----------------------------------------------------

import dataclasses  # noqa: E402
import functools  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ..core.behaviour import MergeKind  # noqa: E402
from ..ops.dense_table import (  # noqa: E402
    NEG_INF,
    masked_topk,
    observables_equal,
    observe_value,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TopkDenseState:
    """Per-id best-score table [R, NK, I]; the bounded top-K observable is
    derived. The dense lattice keeps every id's max (join = elementwise
    max), which refines the scalar bounded state without changing the
    observable — eviction is a reader-side concern on TPU."""

    best_score: jax.Array  # i32[R, NK, I]; NEG_INF = never seen


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TopkOps:
    key: jax.Array  # i32[R, B]
    id: jax.Array  # i32[R, B]
    score: jax.Array  # i32[R, B]
    valid: jax.Array  # bool[R, B]


class TopkDense:
    type_name = "topk"
    merge_kind = MergeKind.JOIN

    def __init__(self, n_ids: int, size: int = 100):
        self.I = n_ids
        self.K = size

    def init(self, n_replicas: int, n_keys: int = 1) -> TopkDenseState:
        return TopkDenseState(
            best_score=jnp.full((n_replicas, n_keys, self.I), NEG_INF, jnp.int32)
        )

    @functools.partial(jax.jit, static_argnums=0)
    def apply_ops(self, state: TopkDenseState, ops: TopkOps):
        NK = state.best_score.shape[1]

        def per_replica(score, key, id_, s, valid):
            k = jnp.where(valid, key, NK)
            return score.at[k, id_].max(s, mode="drop")

        return (
            TopkDenseState(
                jax.vmap(per_replica)(
                    state.best_score, ops.key, ops.id, ops.score, ops.valid
                )
            ),
            None,
        )

    @functools.partial(jax.jit, static_argnums=0)
    def merge(self, a: TopkDenseState, b: TopkDenseState):
        return TopkDenseState(jnp.maximum(a.best_score, b.best_score))

    @functools.partial(jax.jit, static_argnums=0)
    def observe(self, state: TopkDenseState):
        return masked_topk(state.best_score, self.K)

    def value(self, state: TopkDenseState):
        return observe_value(self.observe, state)

    def equal(self, a, b) -> bool:
        return observables_equal(self.observe(a), self.observe(b))


def make_dense(n_ids: int, size: int = 100) -> TopkDense:
    return TopkDense(n_ids=n_ids, size=size)


registry.register("topk", dense_factory=make_dense)
