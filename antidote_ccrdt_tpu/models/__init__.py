from . import average, topk, topk_rmv, topk_rmv_dense, leaderboard, wordcount  # noqa: F401
