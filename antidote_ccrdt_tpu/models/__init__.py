from . import average, topk, topk_rmv, leaderboard, wordcount  # noqa: F401
