"""topk_rmv: top-K with add-wins element removal via per-id vector clocks.

Reference: ``src/antidote_ccrdt_topk_rmv.erl``. The state is a 6-tuple
``{Observed, Masked, Removals, Vc, Min, Size}`` (``:67-74``):

* ``observed`` — id -> best visible element, at most ``size`` entries;
* ``masked``  — id -> set of *all* live adds (the history that removal
  filters; an add survives a removal iff its ts is newer than the removal
  vc at its origin DC — the add-wins core, ``:258-260``);
* ``removals`` — id -> vector-clock tombstone (``:64``);
* ``vc`` — max timestamp per DC over every add this replica has seen
  (``:233``);
* ``min`` — cached smallest observed element (``:399-406``).

Elements are ``(score, id, (dc, ts))`` triples ordered by ``cmp``
(score, then id, then ts — ``:390-395``); ``NIL`` is the reference's
``{nil, nil, nil}``.

Extra-op generation (``antidote_ccrdt.erl:37-40``): `update` returns ops to
re-ship when (a) an add arrives for an already-removed element — re-broadcast
the stored removal (``:234-237``) — or (b) a removal uncovers a masked
element which gets promoted into observed (``:291-295``).
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, NamedTuple, Optional, Tuple

from ..core import serial
from ..core.behaviour import EffectOp, PrepareOp, registry
from ..core.clock import ClockContext

# (score, id, (dc, ts)) — internal element order, and (None, None, None) nil.
Elem = Tuple[Any, Any, Any]
Vc = Dict[Any, int]
NIL: Elem = (None, None, None)


class TopkRmvState(NamedTuple):
    observed: Dict[Any, Elem]
    masked: Dict[Any, FrozenSet[Elem]]
    removals: Dict[Any, Vc]
    vc: Vc
    min: Elem
    size: int


def _cmp(a: Elem, b: Elem) -> bool:
    """Strict 'a beats b' total order: score, then id, then ts (topk_rmv.erl:390-395).

    nil never beats anything; anything beats nil."""
    if a == NIL:
        return False
    if b == NIL:
        return True
    s1, i1, (_, t1) = a
    s2, i2, (_, t2) = b
    return s1 > s2 or (s1 == s2 and i1 > i2) or (s1 == s2 and i1 == i2 and t1 > t2)


def _vc_get(vc: Vc, dc: Any) -> int:
    return vc.get(dc, 0)


def _vc_update(vc: Vc, dc: Any, ts: int) -> Vc:
    out = dict(vc)
    out[dc] = max(ts, out.get(dc, ts))
    return out


def _merge_vcs(a: Vc, b: Vc) -> Vc:
    out = dict(a)
    for k, t in b.items():
        out[k] = max(t, out[k]) if k in out else t
    return out


def _min_observed(observed: Dict[Any, Elem]) -> Elem:
    """Smallest observed element by natural term order (topk_rmv.erl:399-406)."""
    if not observed:
        return NIL
    return min(observed.values())


class TopkRmvScalar:
    type_name = "topk_rmv"

    def new(self, size: int = 100) -> TopkRmvState:
        assert isinstance(size, int) and size > 0
        return TopkRmvState({}, {}, {}, {}, NIL, size)

    def value(self, state: TopkRmvState) -> list:
        return [(i, s) for (s, i, _) in state.observed.values()]

    def downstream(
        self, op: PrepareOp, state: TopkRmvState, ctx: ClockContext
    ) -> Optional[EffectOp]:
        kind, payload = op
        if kind == "add":
            # Stamp with (dc, time) — the reference's only shim calls
            # (topk_rmv.erl:104-105), here explicit via ctx.
            id_, score = payload
            dc, ts = ctx.stamp()
            elem_internal = (score, id_, (dc, ts))
            if id_ in state.observed:
                changes = _cmp(elem_internal, state.observed[id_])
            else:
                changes = _cmp(elem_internal, state.min)
            tag = "add" if changes else "add_r"
            return (tag, (id_, score, (dc, ts)))
        if kind == "rmv":
            id_ = payload
            if id_ not in state.masked:
                return None
            tag = "rmv" if id_ in state.observed else "rmv_r"
            return (tag, (id_, dict(state.vc)))
        raise ValueError(f"unsupported op {op!r}")

    def update(self, effect: EffectOp, state: TopkRmvState) -> Tuple[TopkRmvState, list]:
        kind, payload = effect
        if kind in ("add", "add_r"):
            id_, score, ts = payload
            return self._add(id_, score, ts, state)
        if kind in ("rmv", "rmv_r"):
            id_, vc = payload
            return self._rmv(id_, vc, state)
        raise ValueError(f"unsupported effect {effect!r}")

    def _add(self, id_, score, ts, state: TopkRmvState):
        dc, t = ts
        vc1 = _vc_update(state.vc, dc, t)
        rmv_vc = state.removals.get(id_, {})
        if _vc_get(rmv_vc, dc) >= t:
            # Add dominated by a stored tombstone: state unchanged except the
            # clock advance, and the removal is re-broadcast (:234-237).
            new_state = state._replace(vc=vc1)
            return new_state, [("rmv", (id_, dict(rmv_vc)))]
        elem = (score, id_, ts)
        masked = dict(state.masked)
        masked[id_] = masked.get(id_, frozenset()) | {elem}
        observed, min_ = self._recompute_observed(
            state.observed, state.min, state.size, id_, elem
        )
        return TopkRmvState(observed, masked, state.removals, vc1, min_, state.size), []

    def _recompute_observed(self, observed, min_, size, id_, elem):
        """topk_rmv.erl:302-334."""
        if id_ in observed:
            old = observed[id_]
            if _cmp(elem, old):
                new_obs = dict(observed)
                new_obs[id_] = elem
                new_min = _min_observed(new_obs) if old == min_ else min_
                return new_obs, new_min
            return observed, min_
        if len(observed) < size:
            new_obs = dict(observed)
            new_obs[id_] = elem
            new_min = elem if (_cmp(min_, elem) or min_ == NIL) else min_
            return new_obs, new_min
        if _cmp(elem, min_):
            min_id = min_[1]
            new_obs = dict(observed)
            del new_obs[min_id]
            new_obs[id_] = elem
            return new_obs, _min_observed(new_obs)
        return observed, min_

    def _rmv(self, id_, vc_rmv: Vc, state: TopkRmvState):
        """topk_rmv.erl:252-298."""
        removals = dict(state.removals)
        removals[id_] = _merge_vcs(removals.get(id_, {}), vc_rmv)
        masked = dict(state.masked)
        if id_ in masked:
            # add-wins filter: survive iff strictly newer than the removal
            # vc at the add's origin DC (:258-260).
            kept = frozenset(
                e for e in masked[id_] if e[2][1] > _vc_get(vc_rmv, e[2][0])
            )
            if kept:
                masked[id_] = kept
            else:
                del masked[id_]
        impacts = False
        if id_ in state.observed:
            _, _, (odc, ots) = state.observed[id_]
            impacts = _vc_get(vc_rmv, odc) >= ots
        if not impacts:
            return state._replace(masked=masked, removals=removals), []
        tmp_obs = dict(state.observed)
        removed_elem = tmp_obs.pop(id_)
        # Promotion scan over the whole masked map (:276-281): best live
        # element of every non-observed id, by natural term order.
        candidates = [
            max(elems) for i, elems in masked.items() if i not in tmp_obs
        ]
        if not candidates:
            new_min = _min_observed(tmp_obs) if removed_elem == state.min else state.min
            return (
                TopkRmvState(tmp_obs, masked, removals, state.vc, new_min, state.size),
                [],
            )
        new_elem = max(candidates)
        s, i, t = new_elem
        tmp_obs[i] = new_elem
        new_state = TopkRmvState(
            tmp_obs, masked, removals, state.vc, _min_observed(tmp_obs), state.size
        )
        return new_state, [("add", (i, s, t))]

    def require_state_downstream(self, op: PrepareOp) -> bool:
        return True

    def is_operation(self, op: Any) -> bool:
        if not (isinstance(op, tuple) and len(op) == 2):
            return False
        kind, payload = op
        if kind == "add":
            return (
                isinstance(payload, tuple)
                and len(payload) == 2
                and all(isinstance(x, int) for x in payload)
            )
        if kind == "rmv":
            return isinstance(payload, int)
        return False

    def is_replicate_tagged(self, effect: EffectOp) -> bool:
        return effect[0] in ("add_r", "rmv_r")

    def can_compact(self, e1: EffectOp, e2: EffectOp) -> bool:
        """topk_rmv.erl:178-194."""
        k1, k2 = e1[0], e2[0]
        if (k1, k2) in (("add", "add"), ("add_r", "add")):
            return e1[1][0] == e2[1][0]
        if k1 in ("add", "add_r") and k2 in ("rmv", "rmv_r"):
            if (k1, k2) == ("add", "rmv_r"):
                return False
            id1, _, (dc, ts) = e1[1]
            id2, vc = e2[1]
            return id1 == id2 and _vc_get(vc, dc) >= ts
        if k1 in ("rmv", "rmv_r") and k2 in ("rmv", "rmv_r"):
            return e1[1][0] == e2[1][0]
        return False

    def compact_ops(self, e1: EffectOp, e2: EffectOp):
        """topk_rmv.erl:197-223. None marks the dead slot."""
        k1, k2 = e1[0], e2[0]
        if (k1, k2) == ("add", "add"):
            id1, s1, t1 = e1[1]
            id2, s2, t2 = e2[1]
            if s1 > s2:
                return ("add", (id1, s1, t1)), ("add_r", (id2, s2, t2))
            return ("add_r", (id1, s1, t1)), ("add", (id2, s2, t2))
        if (k1, k2) == ("add_r", "add"):
            _, s1, t1 = e1[1]
            _, s2, t2 = e2[1]
            if s1 == s2 and t1 == t2:
                return None, e2
            return e1, e2
        if k1 in ("add", "add_r") and k2 in ("rmv", "rmv_r"):
            return None, e2
        if k1 in ("rmv", "rmv_r") and k2 in ("rmv", "rmv_r"):
            id2, vc2 = e2[1]
            vc1 = e1[1][1]
            merged = _merge_vcs(vc1, vc2)
            # rmv absorbs rmv_r: the result is observable if either was
            # (topk_rmv.erl:216-223 — {rmv_r,rmv_r} is the only pair that
            # stays tagged).
            tag = "rmv_r" if (k1, k2) == ("rmv_r", "rmv_r") else "rmv"
            return None, (tag, (id2, merged))
        raise ValueError(f"cannot compact {e1!r}, {e2!r}")

    def equal(self, a: TopkRmvState, b: TopkRmvState) -> bool:
        # Observable state only (topk_rmv.erl:151-153).
        return a.observed == b.observed and a.size == b.size

    def to_binary(self, state: TopkRmvState) -> bytes:
        payload = (
            state.observed,
            {k: frozenset(v) for k, v in state.masked.items()},
            state.removals,
            state.vc,
            state.min,
            state.size,
        )
        return serial.dumps_scalar(self.type_name, payload)

    def from_binary(self, data: bytes) -> TopkRmvState:
        name, payload = serial.loads_scalar(data)
        assert name == self.type_name
        obs, masked, removals, vc, min_, size = payload
        return TopkRmvState(obs, dict(masked), removals, vc, tuple(min_), size)


registry.register("topk_rmv", scalar=TopkRmvScalar(), generates_extra_operations=True)
