"""Key/id-space sharding: scale one huge CRDT instance across a mesh.

SURVEY.md §5 maps the reference's missing "long-context" axis onto CCRDTs:
the analogous scaling dimension is the *element-id space* of a single huge
instance (millions of ids in one top-K), sharded across devices the way
sequence-parallel attention shards tokens. The design mirrors the
ring/Ulysses bandwidth argument:

* **state** lives sharded: each device owns a contiguous id range of the
  slot/tombstone tables ([..., I_local, ...]) — the big arrays never move;
* **ops** are broadcast (they are small); each shard masks the batch to its
  own id range and applies it locally — no all-to-all of state;
* **reads** exchange only the top-K *frontier* per shard (K entries, not
  I_local) via `all_gather` and re-rank globally — the collective payload
  is O(K * n_shards), the id-space analog of exchanging KV blocks instead
  of full activations.

`hierarchical_all_reduce` composes the inter-DC reconciliation over a
two-level (dcn, ici) mesh: lattice all-reduce inside each host over ICI
first, then across hosts over DCN — the standard hierarchical-collective
layout for multi-host TPU pods, applied to the CRDT join.

No component in the reference corresponds to this file (its replication is
single-key op shipping, SURVEY.md §2 "Parallelism" checklist); this is the
TPU-native capability the rebuild owes in its place.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.topk_rmv_dense import Observed, TopkRmvDense, TopkRmvOps, make_dense
from ..utils.jaxcompat import shard_map
from .dist import lattice_all_reduce


def make_mesh2(n_dcn: int, n_dc: int, n_key: int = 1, devices=None) -> Mesh:
    """A (dcn, dc, key) mesh: host groups x replica shards x id shards.
    'dc' collectives ride ICI; 'dcn' crosses the data-center network."""
    devices = devices if devices is not None else jax.devices()
    n = n_dcn * n_dc * n_key
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
    return Mesh(
        np.asarray(devices[:n]).reshape(n_dcn, n_dc, n_key),
        ("dcn", "dc", "key"),
    )


def hierarchical_all_reduce(
    x: Any,
    merge: Callable[[Any, Any], Any],
    mesh: Mesh,
    ici_axis: str = "dc",
    dcn_axis: str = "dcn",
):
    """All-reduce a pytree with the CRDT merge over two mesh levels:
    ICI-local first (cheap, high-bandwidth), then one exchange per host
    group over DCN — total DCN traffic is 1/|ici| of a flat all-reduce."""
    x = lattice_all_reduce(x, ici_axis, merge, mesh.shape[ici_axis])
    return lattice_all_reduce(x, dcn_axis, merge, mesh.shape[dcn_axis])


def _join_over_mesh_axes(st: Any, merge, mesh: Mesh, dc_axis: str) -> Any:
    """Inside shard_map: replica join over 'dc' (and 'dcn' when the mesh
    has one) — shared by every id-sharded engine's merge_replicas."""
    out = lattice_all_reduce(st, dc_axis, merge, mesh.shape[dc_axis])
    if "dcn" in mesh.shape:
        out = lattice_all_reduce(out, "dcn", merge, mesh.shape["dcn"])
    return out


def _gather_frontier(tree: Any, axis: str) -> Any:
    """Inside shard_map: all_gather each [R, NK, K] frontier leaf over the
    id-shard axis and flatten the shard axis into the trailing candidate
    axis -> [R, NK, n_shards*K]. The collective payload is O(K) per shard
    — the whole point of the frontier-exchange read path."""
    g = jax.tree.map(lambda a: lax.all_gather(a, axis), tree)
    return jax.tree.map(
        lambda a: jnp.moveaxis(a, 0, -2).reshape(a.shape[1], a.shape[2], -1), g
    )


@dataclasses.dataclass(frozen=True)
class IdShardedTopkRmv:
    """One topk_rmv instance whose id space is sharded over a mesh axis.

    `inner` is the per-shard dense engine (n_ids = I_global / n_shards);
    every state it produces has layout [R, NK, I_local, ...] per shard.
    The global engine presents:

    * `init()` — sharded fresh state placed on the mesh;
    * `apply_ops(state, ops)` — ops carry GLOBAL ids; each shard masks to
      its range and rebases (ops are replicated over 'key', state stays
      put);
    * `observe(state)` — per-shard top-K, frontier all_gather over 'key',
      global re-rank (ids reported global);
    * `merge_replicas(state)` — the inter-DC join over 'dc' (and 'dcn' if
      present), run entirely shard-local: the join never crosses id
      ranges, so id sharding composes with replica merging for free.
    """

    inner: TopkRmvDense
    mesh: Mesh
    n_replicas: int
    key_axis: str = "key"
    dc_axis: str = "dc"

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.key_axis]

    @property
    def i_global(self) -> int:
        return self.inner.I * self.n_shards

    def _state_spec(self):
        """Per-leaf PartitionSpecs. The slot/tombstone tables shard their
        id axis (axis 2); vc and lossy have no id axis, so the sharded
        layout gives them an explicit shard axis at position 1 (each
        shard's vc covers only the adds it saw — the global vc is the max
        over shards)."""
        from ..models.topk_rmv_dense import TopkRmvDenseState

        dc, key = self.dc_axis, self.key_axis
        table = P(dc, None, key)
        return TopkRmvDenseState(
            slot_score=table,
            slot_dc=table,
            slot_ts=table,
            rmv_vc=table,
            vc=P(dc, key),
            lossy=P(dc, key),
        )

    def init(self) -> Any:
        """Sharded fresh state: tables [R, NK, I_global, ...], vc/lossy
        carry the extra shard axis [R, n_shards, NK, ...]."""
        R, NSH, NK = self.n_replicas, self.n_shards, 1
        Dd, I_g, M = self.inner.D, self.i_global, self.inner.M
        from ..models.topk_rmv_dense import TopkRmvDenseState
        from ..ops.dense_table import NEG_INF

        state = TopkRmvDenseState(
            slot_score=jnp.full((R, NK, I_g, M), NEG_INF, jnp.int32),
            slot_dc=jnp.zeros((R, NK, I_g, M), jnp.int32),
            slot_ts=jnp.zeros((R, NK, I_g, M), jnp.int32),
            rmv_vc=jnp.zeros((R, NK, I_g, Dd), jnp.int32),
            vc=jnp.zeros((R, NSH, NK, Dd), jnp.int32),
            lossy=jnp.zeros((R, NSH, NK), bool),
        )
        specs = self._state_spec()
        return jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            state,
            specs,
        )

    @staticmethod
    def _to_local(st):
        """Inside shard_map: drop vc/lossy's singleton shard axis so the
        leaves match the inner engine's layout."""
        from ..models.topk_rmv_dense import TopkRmvDenseState

        return TopkRmvDenseState(
            slot_score=st.slot_score,
            slot_dc=st.slot_dc,
            slot_ts=st.slot_ts,
            rmv_vc=st.rmv_vc,
            vc=st.vc[:, 0],
            lossy=st.lossy[:, 0],
        )

    @staticmethod
    def _from_local(st):
        from ..models.topk_rmv_dense import TopkRmvDenseState

        return TopkRmvDenseState(
            slot_score=st.slot_score,
            slot_dc=st.slot_dc,
            slot_ts=st.slot_ts,
            rmv_vc=st.rmv_vc,
            vc=st.vc[:, None],
            lossy=st.lossy[:, None],
        )

    # -- sharded application ------------------------------------------------

    def _mask_to_shard(self, ops: TopkRmvOps) -> TopkRmvOps:
        """Inside shard_map: keep only ops whose GLOBAL id falls in this
        shard's range, rebased to local ids; foreign ops become padding.
        Runs on every shard over the full (replicated) op batch — O(B)
        elementwise work instead of an all-to-all exchange."""
        I_loc = self.inner.I
        shard = lax.axis_index(self.key_axis)
        lo = shard * I_loc
        a_mine = (ops.add_id >= lo) & (ops.add_id < lo + I_loc)
        r_mine = (ops.rmv_id >= lo) & (ops.rmv_id < lo + I_loc)
        return TopkRmvOps(
            add_key=ops.add_key,
            add_id=jnp.where(a_mine, ops.add_id - lo, 0),
            add_score=ops.add_score,
            add_dc=ops.add_dc,
            add_ts=jnp.where(a_mine, ops.add_ts, 0),  # 0 = padding
            rmv_key=ops.rmv_key,
            rmv_id=jnp.where(r_mine, ops.rmv_id - lo, -1),  # -1 = padding
            rmv_vc=ops.rmv_vc,
        )

    # Compiled entry points are built once per instance (cached_property
    # writes through the instance __dict__, which frozen dataclasses keep)
    # — rebuilding jax.jit(shard_map(closure)) per call would retrace and
    # recompile every time (jit caches on function identity).

    @functools.cached_property
    def _apply_compiled(self):
        spec_state = self._state_spec()
        spec_ops = TopkRmvOps(*([P(self.dc_axis)] * 8))

        def local(st, op):
            op = self._mask_to_shard(op)
            st2, _ = self.inner.apply_ops(
                self._to_local(st), op, collect_dominated=False
            )
            return self._from_local(st2)

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state, spec_ops),
                out_specs=spec_state,
                check_vma=False,
            )
        )

    def apply_ops(self, state: Any, ops: TopkRmvOps) -> Any:
        """ops leaves are [R, B] with global ids, replicated over 'key' and
        sharded over 'dc' like the state's replica axis."""
        return self._apply_compiled(state, ops)

    # -- reads: frontier exchange ------------------------------------------

    @functools.cached_property
    def _observe_compiled(self):
        spec_state = self._state_spec()
        K = self.inner.K
        I_loc = self.inner.I

        def local(st):
            obs = self.inner.observe(self._to_local(st))  # [R_loc, NK, K] local ids
            shard = lax.axis_index(self.key_axis)
            gids = jnp.where(obs.valid, obs.ids + shard * I_loc, -1)
            frontier = Observed(gids, obs.scores, obs.dcs, obs.tss, obs.valid)
            cat = _gather_frontier(frontier, self.key_axis)  # [R, NK, S*K]
            ns, ni, nt, dc_f, valid_f = lax.sort(
                (
                    jnp.where(cat.valid, -cat.scores, -jnp.int32(-(2**31 - 1))),
                    -cat.ids,
                    -cat.tss,
                    cat.dcs,
                    cat.valid,
                ),
                num_keys=3,
                dimension=-1,
            )
            return Observed(
                ids=-ni[..., :K],
                scores=-ns[..., :K],
                dcs=dc_f[..., :K],
                tss=-nt[..., :K],
                valid=valid_f[..., :K],
            )

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state,),
                out_specs=P(self.dc_axis, None, None),
                check_vma=False,
            )
        )

    def observe(self, state: Any) -> Observed:
        """Global observable top-K: local top-K per shard (payload K, not
        I_local), all_gather over the id shards, re-rank by the reference
        cmp order (score desc, id desc, ts desc)."""
        return self._observe_compiled(state)

    # -- inter-DC reconciliation -------------------------------------------

    @functools.cached_property
    def _merge_compiled(self):
        spec_state = self._state_spec()

        def local(st):
            merged = _join_over_mesh_axes(
                self._to_local(st), self.inner.merge, self.mesh, self.dc_axis
            )
            return self._from_local(merged)

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state,),
                out_specs=spec_state,
                check_vma=False,
            )
        )

    def merge_replicas(self, state: Any) -> Any:
        """Join all replica rows over the 'dc' axis (and 'dcn' when the
        mesh has one), shard-local in the id dimension: every replica ends
        up with the converged state for the shard's id range."""
        return self._merge_compiled(state)


def make_id_sharded_topk_rmv(
    mesh: Mesh,
    n_ids_global: int,
    n_dcs: int,
    size: int = 100,
    slots_per_id: int = 4,
    n_replicas: int | None = None,
    key_axis: str = "key",
    dc_axis: str = "dc",
) -> IdShardedTopkRmv:
    n_shards = mesh.shape[key_axis]
    assert n_ids_global % n_shards == 0, (n_ids_global, n_shards)
    inner = make_dense(
        n_ids=n_ids_global // n_shards,
        n_dcs=n_dcs,
        size=size,
        slots_per_id=slots_per_id,
    )
    if n_replicas is None:
        n_replicas = mesh.shape[dc_axis]
    return IdShardedTopkRmv(
        inner=inner,
        mesh=mesh,
        n_replicas=n_replicas,
        key_axis=key_axis,
        dc_axis=dc_axis,
    )


# --- shared skeleton: score-table engines (leaderboard, topk) -------------


@dataclasses.dataclass(frozen=True)
class _ShardedScoreTable:
    """Shared id-space-sharded skeleton for the flat score-table engines
    (whose dense state is [R, NK, P]-shaped planes and whose observe
    returns (ids, scores, valid)): per-shard masked application, frontier
    exchange + (score desc, id desc) re-rank, shard-local replica join.
    Subclasses supply the state spec/init, the op masker, and the local
    id-range size. Compiled entry points are built once per instance
    (cached_property writes through the instance __dict__, which frozen
    dataclasses keep) — rebuilding jit(shard_map(closure)) per call would
    retrace and recompile every time."""

    inner: Any
    mesh: Mesh
    n_replicas: int
    key_axis: str = "key"
    dc_axis: str = "dc"

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.key_axis]

    def _local_size(self) -> int:
        raise NotImplementedError

    def _state_spec(self):
        raise NotImplementedError

    def _ops_spec(self):
        raise NotImplementedError

    def _mask_to_shard(self, ops: Any) -> Any:
        raise NotImplementedError

    def _place(self, state: Any) -> Any:
        return jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            state,
            self._state_spec(),
        )

    @functools.cached_property
    def _apply_compiled(self):
        spec_state = self._state_spec()
        spec_ops = self._ops_spec()

        def local(st, op):
            st2, _ = self.inner.apply_ops(st, self._mask_to_shard(op))
            return st2

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state, spec_ops),
                out_specs=spec_state,
                check_vma=False,
            )
        )

    def apply_ops(self, state: Any, ops: Any) -> Any:
        return self._apply_compiled(state, ops)

    @functools.cached_property
    def _observe_compiled(self):
        spec_state = self._state_spec()
        K = self.inner.K
        loc = self._local_size()

        def local(st):
            ids, scores, valid = self.inner.observe(st)
            gids = jnp.where(valid, ids + lax.axis_index(self.key_axis) * loc, -1)
            cat_i, cat_s, cat_v = _gather_frontier(
                (gids, scores, valid), self.key_axis
            )
            ns, ni, v_f = lax.sort(
                (
                    jnp.where(cat_v, -cat_s, jnp.int32(2**31 - 1)),
                    -cat_i,
                    cat_v,
                ),
                num_keys=2,
                dimension=-1,
            )
            return -ni[..., :K], -ns[..., :K], v_f[..., :K]

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state,),
                out_specs=(
                    P(self.dc_axis, None, None),
                    P(self.dc_axis, None, None),
                    P(self.dc_axis, None, None),
                ),
                check_vma=False,
            )
        )

    def observe(self, state: Any):
        """Global top-K: per-shard masked top-K (payload K, not the local
        table width), frontier all_gather over the id shards, global
        re-rank by (score desc, id desc) — the shared cmp order of
        topk.erl:83 / leaderboard.erl:289-294."""
        return self._observe_compiled(state)

    @functools.cached_property
    def _merge_compiled(self):
        spec_state = self._state_spec()

        def local(st):
            return _join_over_mesh_axes(
                st, self.inner.merge, self.mesh, self.dc_axis
            )

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state,),
                out_specs=spec_state,
                check_vma=False,
            )
        )

    def merge_replicas(self, state: Any) -> Any:
        return self._merge_compiled(state)


# --- player-space-sharded leaderboard -------------------------------------


@dataclasses.dataclass(frozen=True)
class IdShardedLeaderboard(_ShardedScoreTable):
    """One leaderboard whose PLAYER space is sharded over a mesh axis —
    the second instantiation of the long-context-analog design (cf.
    `IdShardedTopkRmv`): the lattice (per-player max, ban-or) has no
    vc/lossy side planes, so the sharded layout is purely the player axis
    and the replica join is shard-local elementwise max/or. Ban-wins
    (leaderboard.erl:21-27) survives sharding: bans live on the banned
    player's shard and mask its frontier contribution."""

    @property
    def p_global(self) -> int:
        return self.inner.P * self.n_shards

    def _local_size(self) -> int:
        return self.inner.P

    def _state_spec(self):
        from ..models.leaderboard import LeaderboardDenseState

        table = P(self.dc_axis, None, self.key_axis)
        return LeaderboardDenseState(best_score=table, banned=table)

    def _ops_spec(self):
        from ..models.leaderboard import LeaderboardOps

        return LeaderboardOps(*([P(self.dc_axis)] * 7))

    def init(self) -> Any:
        from ..models.leaderboard import LeaderboardDenseState
        from ..ops.dense_table import NEG_INF

        R, NK, Pg = self.n_replicas, 1, self.p_global
        return self._place(
            LeaderboardDenseState(
                best_score=jnp.full((R, NK, Pg), NEG_INF, jnp.int32),
                banned=jnp.zeros((R, NK, Pg), bool),
            )
        )

    def _mask_to_shard(self, ops: Any) -> Any:
        from ..models.leaderboard import LeaderboardOps

        P_loc = self.inner.P
        lo = lax.axis_index(self.key_axis) * P_loc
        a_mine = ops.add_valid & (ops.add_id >= lo) & (ops.add_id < lo + P_loc)
        b_mine = ops.ban_valid & (ops.ban_id >= lo) & (ops.ban_id < lo + P_loc)
        return LeaderboardOps(
            add_key=ops.add_key,
            add_id=jnp.where(a_mine, ops.add_id - lo, 0),
            add_score=ops.add_score,
            add_valid=a_mine,
            ban_key=ops.ban_key,
            ban_id=jnp.where(b_mine, ops.ban_id - lo, 0),
            ban_valid=b_mine,
        )


def make_id_sharded_leaderboard(
    mesh: Mesh,
    n_players_global: int,
    size: int = 100,
    n_replicas: int | None = None,
    key_axis: str = "key",
    dc_axis: str = "dc",
) -> IdShardedLeaderboard:
    from ..models.leaderboard import make_dense as mk_lb

    n_shards = mesh.shape[key_axis]
    assert n_players_global % n_shards == 0, (n_players_global, n_shards)
    inner = mk_lb(n_players=n_players_global // n_shards, size=size)
    if n_replicas is None:
        n_replicas = mesh.shape[dc_axis]
    return IdShardedLeaderboard(
        inner=inner,
        mesh=mesh,
        n_replicas=n_replicas,
        key_axis=key_axis,
        dc_axis=dc_axis,
    )


# --- id-space-sharded topk (bounded score table, no bans) -----------------


@dataclasses.dataclass(frozen=True)
class IdShardedTopk(_ShardedScoreTable):
    """`topk`'s turn on the shared skeleton: the dense engine is a single
    best-score table (models/topk.py), i.e. the leaderboard pattern minus
    the ban plane."""

    @property
    def i_global(self) -> int:
        return self.inner.I * self.n_shards

    def _local_size(self) -> int:
        return self.inner.I

    def _state_spec(self):
        from ..models.topk import TopkDenseState

        return TopkDenseState(best_score=P(self.dc_axis, None, self.key_axis))

    def _ops_spec(self):
        from ..models.topk import TopkOps

        return TopkOps(*([P(self.dc_axis)] * 4))

    def init(self) -> Any:
        from ..models.topk import TopkDenseState
        from ..ops.dense_table import NEG_INF

        return self._place(
            TopkDenseState(
                best_score=jnp.full(
                    (self.n_replicas, 1, self.i_global), NEG_INF, jnp.int32
                )
            )
        )

    def _mask_to_shard(self, ops: Any) -> Any:
        from ..models.topk import TopkOps

        I_loc = self.inner.I
        lo = lax.axis_index(self.key_axis) * I_loc
        mine = ops.valid & (ops.id >= lo) & (ops.id < lo + I_loc)
        return TopkOps(
            key=ops.key,
            id=jnp.where(mine, ops.id - lo, 0),
            score=ops.score,
            valid=mine,
        )


def make_id_sharded_topk(
    mesh: Mesh,
    n_ids_global: int,
    size: int = 100,
    n_replicas: int | None = None,
    key_axis: str = "key",
    dc_axis: str = "dc",
) -> IdShardedTopk:
    from ..models.topk import make_dense as mk_topk

    n_shards = mesh.shape[key_axis]
    assert n_ids_global % n_shards == 0, (n_ids_global, n_shards)
    inner = mk_topk(n_ids=n_ids_global // n_shards, size=size)
    if n_replicas is None:
        n_replicas = mesh.shape[dc_axis]
    return IdShardedTopk(
        inner=inner,
        mesh=mesh,
        n_replicas=n_replicas,
        key_axis=key_axis,
        dc_axis=dc_axis,
    )


# --- vocab-space-sharded wordcount (MONOID: psum reconciliation) ----------


@dataclasses.dataclass(frozen=True)
class VocabShardedWordcount:
    """One wordcount instance whose VOCAB space is sharded over a mesh
    axis — the MONOID member of the id-space-sharding family (SURVEY §5
    key-space sharding row). Same data movement as the JOIN engines:
    the count table never moves, ops are replicated over 'key' and each
    shard masks the token batch to its bucket range; but reconciliation
    is a `psum` over 'dc' (replica rows are deltas, MergeKind.MONOID) —
    no frontier exchange, reads are already local per shard.

    Out-of-global-range tokens are counted in `lost` by shard 0 only
    (every shard sees every op; without a canonical owner the lost
    counter would multiply by n_shards). Within-shard overflow cannot
    happen: the mask rebases tokens into [0, V_local).

    Compiled entry points are built once per instance (cached_property —
    cf. _ShardedScoreTable's retrace note)."""

    inner: Any  # WordcountDense over V_local buckets
    mesh: Mesh
    n_replicas: int
    key_axis: str = "key"
    dc_axis: str = "dc"

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.key_axis]

    @property
    def v_global(self) -> int:
        return self.inner.V * self.n_shards

    def _state_spec(self):
        from ..models.wordcount import WordcountDenseState

        # counts shard their bucket axis; lost gains an explicit shard
        # axis at position 1 (same move as IdShardedTopkRmv's vc/lossy).
        return WordcountDenseState(
            counts=P(self.dc_axis, None, self.key_axis),
            lost=P(self.dc_axis, self.key_axis),
        )

    def init(self) -> Any:
        from ..models.wordcount import WordcountDenseState

        R, NK = self.n_replicas, 1
        state = WordcountDenseState(
            counts=jnp.zeros((R, NK, self.v_global), jnp.int32),
            lost=jnp.zeros((R, self.n_shards, NK), jnp.int32),
        )
        return jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            state,
            self._state_spec(),
        )

    def _mask_to_shard(self, ops: Any) -> Any:
        from ..models.wordcount import WordcountOps

        V_loc = self.inner.V
        shard = lax.axis_index(self.key_axis)
        lo = shard * V_loc
        valid = ops.token >= 0
        mine = valid & (ops.token >= lo) & (ops.token < lo + V_loc)
        # Global overflow: only shard 0 counts it (token V_loc lands in
        # the inner engine's lost path).
        over = valid & (ops.token >= self.v_global) & (shard == 0)
        token = jnp.where(mine, ops.token - lo, jnp.where(over, V_loc, -1))
        return WordcountOps(key=ops.key, token=token)

    @functools.cached_property
    def _apply_compiled(self):
        from ..models.wordcount import WordcountDenseState, WordcountOps

        spec_state = self._state_spec()
        spec_ops = WordcountOps(P(self.dc_axis), P(self.dc_axis))

        def local(st, op):
            st_l = WordcountDenseState(counts=st.counts, lost=st.lost[:, 0])
            st2, _ = self.inner.apply_ops(st_l, self._mask_to_shard(op))
            return WordcountDenseState(
                counts=st2.counts, lost=st2.lost[:, None]
            )

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state, spec_ops),
                out_specs=spec_state,
                check_vma=False,
            )
        )

    def apply_ops(self, state: Any, ops: Any) -> Any:
        """`ops` carry GLOBAL bucket ids, one batch per replica row,
        replicated over the vocab shards (they are small; the table is
        what must not move)."""
        return self._apply_compiled(state, ops)

    @functools.cached_property
    def _reduce_compiled(self):
        from ..models.wordcount import WordcountDenseState

        spec_state = self._state_spec()

        def local(st):
            # Replica rows are deltas: the reconciled value is their SUM
            # (psum over 'dc' — the MONOID plane), shard-local in vocab.
            counts = lax.psum(jnp.sum(st.counts, axis=0), self.dc_axis)
            lost = lax.psum(jnp.sum(st.lost, axis=0), self.dc_axis)
            return WordcountDenseState(counts=counts, lost=lost)

        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec_state,),
                out_specs=WordcountDenseState(
                    counts=P(None, self.key_axis),
                    lost=P(self.key_axis),
                ),
                check_vma=False,
            )
        )

    def global_counts(self, state: Any):
        """Reconciled global (counts [NK, V_global], lost [n_shards, NK])
        — counts stay vocab-sharded on the mesh (the read is local per
        shard); `lost` sums to the global overflow count."""
        return self._reduce_compiled(state)


def make_vocab_sharded_wordcount(
    mesh: Mesh,
    n_buckets_global: int,
    n_replicas: int | None = None,
    key_axis: str = "key",
    dc_axis: str = "dc",
) -> VocabShardedWordcount:
    from ..models.wordcount import make_dense as mk_wc

    n_shards = mesh.shape[key_axis]
    assert n_buckets_global % n_shards == 0, (n_buckets_global, n_shards)
    inner = mk_wc(n_buckets_global // n_shards)
    if n_replicas is None:
        n_replicas = mesh.shape[dc_axis]
    return VocabShardedWordcount(
        inner=inner,
        mesh=mesh,
        n_replicas=n_replicas,
        key_axis=key_axis,
        dc_axis=dc_axis,
    )
