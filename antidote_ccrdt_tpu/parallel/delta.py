"""Delta-state replication for dense lattice states.

Delta-CRDT lineage ("Big(ger) Sets: decomposed delta CRDT Sets in Riak",
PAPERS.md): instead of shipping the whole lattice state on every
anti-entropy round, ship the *join-decomposed delta* — the state
restricted to the rows whose content changed since the last publish. The
reference's own bandwidth lever is `is_replicate_tagged`
(topk_rmv.erl:172-175: ship non-observable effects anyway, but nothing
more than effects); the dense engine's analog operates at the state
plane: a publish round that touched a few thousand of 100k ids ships a
few-hundred-KB delta instead of a ~20MB full state.

Why this is safe with NO special delta-merge kernel: empty rows are the
join identity for every leaf (slots NEG_INF/0, tombstones 0, vc 0, lossy
False), so `expand` lifts a delta back to a full-shape state and the
ordinary engine join applies it. Chaining is the one obligation:
a receiver may apply member M's delta seq k only if it has applied M's
full state or deltas through seq k-1 (unchanged rows are then already
identical on both sides, so joining the expanded delta equals joining
M's full state). On any gap the receiver falls back to M's latest full
snapshot — `parallel.elastic.sweep_deltas` implements exactly that.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TopkRmvDelta:
    """State restricted to changed (replica, key, id) rows.

    `rows` are flat indices into the [R*NK*I] row space; slot/tombstone
    payloads ride per changed row; the small dense leaves (vc, lossy)
    ship whole — they are O(R*NK*D), not O(I)."""

    rows: jax.Array  # i32[n] flat (r*NK + k)*I + id
    slot_score: jax.Array  # i32[n, M]
    slot_dc: jax.Array  # i32[n, M]
    slot_ts: jax.Array  # i32[n, M]
    rmv_vc: jax.Array  # i32[n, D]
    vc: jax.Array  # i32[R, NK, D]
    lossy: jax.Array  # bool[R, NK]


@jax.jit
def _changed_mask(prev: Any, cur: Any) -> jax.Array:
    """bool [R, NK, I]: rows whose join inputs differ. Module-level jit —
    a per-call closure would recompile on every publish (jit caches key
    on function identity; same pathology as utils.validate's report)."""
    return (
        jnp.any(cur.slot_score != prev.slot_score, axis=-1)
        | jnp.any(cur.slot_dc != prev.slot_dc, axis=-1)
        | jnp.any(cur.slot_ts != prev.slot_ts, axis=-1)
        | jnp.any(cur.rmv_vc != prev.rmv_vc, axis=-1)
    )


def state_delta(dense: Any, prev: Any, cur: Any) -> TopkRmvDelta:
    """Rows of `cur` that differ from `prev` (plus the whole small
    leaves). The changed-row mask is one fused device reduction; the row
    gather itself is HOST-side numpy fancy-indexing: the changed-row
    count n differs on every publish, so an eager device gather would
    recompile per distinct n (the mirror of the device scatter pathology
    `expand_delta` avoids). The delta is serialized to bytes right after
    anyway, so pulling the leaves to host here costs one transfer the
    gossip path was about to pay regardless."""
    R, NK, I, M = cur.slot_score.shape
    D = cur.rmv_vc.shape[-1]
    mask = np.asarray(_changed_mask(prev, cur)).reshape(-1)
    rows = np.nonzero(mask)[0].astype(np.int32)
    flat = lambda x, w: np.asarray(x).reshape(R * NK * I, w)  # noqa: E731
    return TopkRmvDelta(
        rows=jnp.asarray(rows),
        slot_score=jnp.asarray(flat(cur.slot_score, M)[rows]),
        slot_dc=jnp.asarray(flat(cur.slot_dc, M)[rows]),
        slot_ts=jnp.asarray(flat(cur.slot_ts, M)[rows]),
        rmv_vc=jnp.asarray(flat(cur.rmv_vc, D)[rows]),
        vc=cur.vc,
        lossy=cur.lossy,
    )


def expand_delta(dense: Any, delta: TopkRmvDelta) -> Any:
    """Lift a delta to a full-shape state whose untouched rows are the
    join identity, so `dense.merge(state, expand_delta(...))` applies it.

    Host-side scatter into identity arrays (numpy), then one device put:
    the expansion runs on the gossip path, not the apply hot path, and a
    host scatter of n rows sidesteps the device scatter pathology
    documented in models/topk_rmv_dense.py."""
    from ..models.topk_rmv_dense import TopkRmvDenseState
    from ..ops.dense_table import NEG_INF

    R, NK, D = delta.vc.shape
    I, M = dense.I, dense.M
    rows = np.asarray(delta.rows)
    score = np.full((R * NK * I, M), NEG_INF, np.int32)
    dc = np.zeros((R * NK * I, M), np.int32)
    ts = np.zeros((R * NK * I, M), np.int32)
    rvc = np.zeros((R * NK * I, D), np.int32)
    score[rows] = np.asarray(delta.slot_score)
    dc[rows] = np.asarray(delta.slot_dc)
    ts[rows] = np.asarray(delta.slot_ts)
    rvc[rows] = np.asarray(delta.rmv_vc)
    shape4 = (R, NK, I, M)
    return TopkRmvDenseState(
        slot_score=jnp.asarray(score.reshape(shape4)),
        slot_dc=jnp.asarray(dc.reshape(shape4)),
        slot_ts=jnp.asarray(ts.reshape(shape4)),
        rmv_vc=jnp.asarray(rvc.reshape(R, NK, I, D)),
        vc=jnp.asarray(delta.vc),
        lossy=jnp.asarray(delta.lossy),
    )


def empty_delta(dense: Any) -> TopkRmvDelta:
    """A shape-valid zero-row delta: the `like` treedef target for
    deserialization (loads_dense checks treedef, not shapes)."""
    z = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
    return TopkRmvDelta(
        rows=z(0), slot_score=z(0, dense.M), slot_dc=z(0, dense.M),
        slot_ts=z(0, dense.M), rmv_vc=z(0, dense.D),
        vc=z(1, 1, dense.D), lossy=jnp.zeros((1, 1), bool),
    )


def delta_nbytes(delta: Any) -> int:
    return sum(
        np.asarray(leaf).nbytes for leaf in jax.tree_util.tree_leaves(delta)
    )


def apply_delta(dense: Any, state: Any, delta: TopkRmvDelta) -> Any:
    """Join a delta into `state` (receiver side)."""
    return dense.merge(state, expand_delta(dense, delta))


# --- generic entrywise deltas (topk / leaderboard / wordcount) ------------


def _split_leaves(state: Any):
    """(paths, leaves, table_paths): table leaves are the [R, NK, P] score/
    count/ban planes (3-D); everything else (lost counters, flags) ships
    whole — they are O(R*NK), not O(P)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    paths = [jax.tree_util.keystr(p) for p, _ in flat]
    leaves = [leaf for _, leaf in flat]
    table = [paths[i] for i, leaf in enumerate(leaves) if leaf.ndim == 3]
    return paths, leaves, table, treedef


def table_delta(dense: Any, prev: Any, cur: Any) -> dict:
    """Entrywise delta for the table-shaped dense states (topk,
    leaderboard, wordcount): every 3-D leaf shares the [R, NK, P] plane, a
    changed-entry index selects the shipped cells.

    Payload semantics follow the engine's merge algebra: JOIN types ship
    the new VALUES (applied via the idempotent join), MONOID types ship
    the numeric DIFFERENCE since the last publish (applied via `+` — a
    monoid delta must not be double-applied, which the chained-seq gossip
    protocol already guarantees). The delta is a plain dict pytree, so
    `core.serial.dumps_dense` ships it unchanged."""
    from ..core.behaviour import MergeKind

    monoid = dense.merge_kind == MergeKind.MONOID
    paths, prevs, table_paths, _ = _split_leaves(prev)
    _, curs, _, _ = _split_leaves(cur)
    by_path = dict(zip(paths, zip(prevs, curs)))

    changed = None
    for p in table_paths:
        pv, cv = by_path[p]
        c = cv != pv
        changed = c if changed is None else (changed | c)
    if changed is None:
        # No O(P) table planes (average: the whole state is O(R*NK)) —
        # everything ships as a "whole" leaf and the index is empty.
        idx = jnp.zeros((0,), jnp.int32)
    else:
        mask = np.asarray(changed).reshape(-1)
        idx = jnp.asarray(np.nonzero(mask)[0].astype(np.int32))

    out: dict = {"idx": idx, "table": {}, "whole": {}}
    for p in paths:
        pv, cv = by_path[p]
        if p in table_paths:
            flat_c = cv.reshape(-1)
            vals = flat_c[idx]
            if monoid:
                vals = vals - pv.reshape(-1)[idx]
            out["table"][p] = vals
        else:
            out["whole"][p] = (
                (cv - pv) if (monoid and jnp.issubdtype(cv.dtype, jnp.integer))
                else cv
            )
    return out


def expand_table_delta(dense: Any, like: Any, delta: dict) -> Any:
    """Lift an entrywise delta onto the identity state (`dense.init` IS
    the join bottom / monoid zero for every type), so `dense.merge` applies
    it — same move as `expand_delta`, type-agnostically."""
    R, NK = jax.tree_util.tree_leaves(like)[0].shape[:2]
    ident = dense.init(R, NK)
    paths, id_leaves, table_paths, treedef = _split_leaves(ident)
    idx = np.asarray(delta["idx"])
    rebuilt = []
    for p, leaf in zip(paths, id_leaves):
        if p in table_paths:
            flat = np.asarray(leaf).reshape(-1).copy()
            flat[idx] = np.asarray(delta["table"][p])
            rebuilt.append(jnp.asarray(flat.reshape(leaf.shape)))
        else:
            rebuilt.append(jnp.asarray(delta["whole"][p]))
    return jax.tree_util.tree_unflatten(treedef, rebuilt)


def apply_table_delta(dense: Any, state: Any, delta: dict) -> Any:
    return dense.merge(state, expand_table_delta(dense, state, delta))


# --- engine-generic dispatch (used by the gossip tier) --------------------


def _is_topk_rmv_state(state: Any) -> bool:
    from ..models.topk_rmv_dense import TopkRmvDenseState

    return isinstance(state, TopkRmvDenseState)


def _is_lifted(state: Any) -> bool:
    from .monoid import LiftedMonoidState

    return isinstance(state, LiftedMonoidState)


def _is_monoid_row_delta(delta: Any) -> bool:
    return isinstance(delta, dict) and "ver" in delta and "leaves" in delta


def make_delta(dense: Any, prev: Any, cur: Any) -> Any:
    """Engine-generic delta: slot-level for topk_rmv states, row-replace
    for lifted monoid states, entrywise for the flat table engines."""
    if _is_topk_rmv_state(cur):
        return state_delta(dense, prev, cur)
    if _is_lifted(cur):
        from .monoid import monoid_row_delta

        return monoid_row_delta(dense, prev, cur)
    return table_delta(dense, prev, cur)


def apply_any_delta(dense: Any, state: Any, delta: Any) -> Any:
    if isinstance(delta, TopkRmvDelta):
        return apply_delta(dense, state, delta)
    if _is_monoid_row_delta(delta):
        from .monoid import apply_monoid_row_delta

        return apply_monoid_row_delta(dense, state, delta)
    return apply_table_delta(dense, state, delta)


def like_delta_for(dense: Any, like_state: Any) -> Any:
    """Treedef target for deserializing this engine's deltas (shapes are
    free; loads_dense checks treedef only)."""
    if _is_topk_rmv_state(like_state):
        return empty_delta(dense)
    if _is_lifted(like_state):
        from .monoid import like_monoid_delta

        return like_monoid_delta(dense, like_state)
    paths, leaves, table_paths, _ = _split_leaves(like_state)
    z = jnp.zeros((0,), jnp.int32)
    return {
        "idx": z,
        "table": {p: z for p in table_paths},
        "whole": {
            p: leaf for p, leaf in zip(paths, leaves) if p not in table_paths
        },
    }


def delta_in_bounds(dense: Any, like_state: Any, delta: Any) -> bool:
    """Config/bounds validation of a decoded peer delta (the gossip fetch
    guard: a treedef-compatible delta from a differently-configured peer
    must be rejected before expansion indexes out of range)."""
    if _is_lifted(like_state):
        from .monoid import monoid_delta_in_bounds

        return _is_monoid_row_delta(delta) and monoid_delta_in_bounds(
            dense, like_state, delta
        )
    R, NK = jax.tree_util.tree_leaves(like_state)[0].shape[:2]
    if isinstance(delta, TopkRmvDelta):
        n_rows = R * NK * dense.I
        n = int(delta.rows.shape[0]) if delta.rows.ndim == 1 else -1
        # Full-shape checks, leading dims included: a treedef-compatible
        # delta from a peer with different R/NK (e.g. n_replicas=1) would
        # otherwise slip through and jnp-broadcast its rows into every
        # local replica inside merge.
        if (
            n < 0
            or tuple(delta.slot_score.shape) != (n, dense.M)
            or tuple(delta.slot_dc.shape) != (n, dense.M)
            or tuple(delta.slot_ts.shape) != (n, dense.M)
            or tuple(delta.rmv_vc.shape) != (n, dense.D)
            or tuple(delta.vc.shape) != (R, NK, dense.D)
            or tuple(delta.lossy.shape) != (R, NK)
        ):
            return False
        rows = np.asarray(delta.rows)
        return bool(
            rows.size == 0 or (rows.min() >= 0 and rows.max() < n_rows)
        )
    paths, leaves, table_paths, _ = _split_leaves(like_state)
    shapes = dict(zip(paths, (leaf.shape for leaf in leaves)))
    n_entries = {p: int(np.prod(shapes[p])) for p in table_paths}
    if set(delta.get("table", {})) != set(table_paths):
        return False
    idx = np.asarray(delta["idx"])
    if idx.ndim != 1 or not np.issubdtype(idx.dtype, np.integer):
        return False
    if idx.size and (idx.min() < 0 or idx.max() >= min(n_entries.values())):
        return False
    # Each table payload must carry exactly one (scalar) value per index —
    # a mismatched length otherwise raises inside expand_table_delta's
    # fancy assignment on the unguarded sweep path.
    for p in table_paths:
        if tuple(np.asarray(delta["table"][p]).shape) != (idx.size,):
            return False
    for p, whole in delta.get("whole", {}).items():
        if p not in shapes or tuple(np.asarray(whole).shape) != shapes[p]:
            return False
    return True
