"""MONOID → JOIN lift: the gossip/elastic plane for average + wordcount.

The reference's host replicates all six types through one delivery path
(`antidote_ccrdt.erl:47-59` makes no type distinction); through round 2
this repo's gossip tier refused MONOID engines because snapshot resync
re-merges peer states, and a monoid `+` double-counts on re-merge. This
module closes that asymmetry with the classic counter-CRDT construction
(the G-counter lift, cf. the delta-CRDT lineage in PAPERS.md): key each
member's contribution and make anti-entropy *replace* slices instead of
adding them.

The dense states already carry the decomposition: every MONOID leaf has a
leading ``[n_replicas, ...]`` axis, and one replica row is exactly one
writer's contribution accumulator. So the lift is:

* ``LiftedMonoidState`` = inner monoid state + ``ver: i32[R]``, a
  per-row version counting how many op batches that row's writer has
  applied.
* ``merge`` = per-row "take the side with the higher version" (ties keep
  the left side). Under the single-writer-per-row contract this is a true
  join: idempotent (re-merging any snapshot, however stale or duplicated,
  changes nothing once the local version caught up), commutative, and
  associative — the properties snapshot gossip actually needs.

Contract (documented, and what `parallel.elastic.owners` provides): each
row has ONE writer at a time, and a row's (version, content) pair is
write-once — version v always denotes the same contents. That contract
forbids applying ops onto a row copy that arrived via gossip (its
version already counts batches the writer would duplicate), so writers
keep contributions and gossip in separate states — `MonoidContributor`
packages the discipline. Crash handoff regenerates an adopted row from
its durable op source into the writer's own contribution state (still
identity there); the regenerated version supersedes the victim's
published prefix by row-replace — no double count. Ownership overlap
during a view flap is safe exactly when op streams are deterministic
(both owners produce identical (ver, content) pairs) — the same
regeneration discipline the JOIN drill already relies on.

Deltas (`monoid_row_delta`) ship whole changed ROWS, self-contained:
each delta carries (row index, version, full row payload), and applying
one replaces any local row with a lower version. No chaining obligation,
no gap resync hazard — duplicated, reordered, or dropped deltas are all
harmless, strictly stronger than the chained-seq protocol JOIN deltas
need. The price is payload ∝ row size rather than touched entries; for
the monoid engines a row is O(NK·V) and a publish ships only the rows
the member owns, so fleet-wide traffic still drops ~n_members× vs full
snapshots.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.behaviour import MergeKind


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LiftedMonoidState:
    """A monoid dense state plus per-replica-row versions.

    ``ver[r]`` counts op batches applied to row r by its writer; the
    lifted join replaces whole rows by version (see module docstring).

    ``swept`` (static metadata, not a device leaf) marks states that have
    been through `merge` — i.e. that may contain rows adopted from gossip.
    The write-once (version, content) contract forbids applying ops onto
    such a state (the adopted rows' versions already count their writers'
    batches; re-applying would double-count under a legitimate version),
    and `apply_ops` enforces it (ADVICE r3 #2). The flag is advisory
    metadata: tree ops that rebuild the dataclass from leaves (device
    puts, checkpoint restore) reset it to False, so it catches the
    in-process misuse pattern, not adversarial laundering."""

    inner: Any
    ver: jax.Array  # i32[R]
    swept: bool = dataclasses.field(default=False, metadata=dict(static=True))


class MonoidLift:
    """JOIN-algebra adapter around a MONOID dense engine.

    Satisfies the `DenseCCRDT` surface (init/apply_ops/merge/observe) so
    the whole gossip tier — `GossipStore`, `sweep`, `sweep_deltas`,
    `DeltaPublisher`, checkpoints, Orbax gossip — takes it unchanged."""

    merge_kind = MergeKind.JOIN

    def __init__(self, inner: Any):
        kind = getattr(inner, "merge_kind", None)
        if kind != MergeKind.MONOID:
            raise ValueError(
                f"MonoidLift wraps MONOID engines; {type(inner).__name__} "
                f"has merge_kind {kind!r} (JOIN engines gossip directly)"
            )
        self.inner = inner
        self.type_name = f"{inner.type_name}_lifted"

    def init(self, n_replicas: int, n_keys: int = 1, **params: Any) -> LiftedMonoidState:
        return LiftedMonoidState(
            inner=self.inner.init(n_replicas, n_keys, **params),
            ver=jnp.zeros((n_replicas,), jnp.int32),
        )

    def apply_ops(
        self, state: LiftedMonoidState, ops: Any,
        owned: Optional[Sequence[int]] = None,
        allow_swept: bool = False, **kw: Any,
    ) -> Tuple[LiftedMonoidState, Any]:
        """Apply one op batch and bump the version of the rows this member
        WRITES. `owned=None` bumps every row (single-process use, where
        the caller owns the whole grid); gossiping members MUST pass their
        owned rows — bumping a row you only padded would shadow its real
        writer's content with your identity row.

        Raises on a state that has been through `merge` (``swept=True``):
        applying ops onto gossip-adopted rows double-counts batches under
        a legitimate version — the exact failure the lift exists to
        prevent. Writers keep a merge-free contribution state
        (`MonoidContributor.own`); `allow_swept=True` is the explicit
        escape hatch for callers that have re-established the write-once
        contract some other way."""
        if state.swept and not allow_swept:
            raise ValueError(
                "apply_ops on a merged (swept) LiftedMonoidState: its rows "
                "may have been adopted from gossip, and re-applying ops "
                "onto them double-counts under a legitimate version. Apply "
                "onto the writer's own contribution state "
                "(MonoidContributor), or pass allow_swept=True if the "
                "write-once contract is re-established."
            )
        new_inner, extras = self.inner.apply_ops(state.inner, ops, **kw)
        R = state.ver.shape[0]
        if owned is None:
            bump = jnp.ones((R,), jnp.int32)
        else:
            b = np.zeros((R,), np.int32)
            b[np.asarray(sorted(owned), np.int64)] = 1
            bump = jnp.asarray(b)
        return LiftedMonoidState(new_inner, state.ver + bump, swept=state.swept), extras

    def merge(self, a: LiftedMonoidState, b: LiftedMonoidState) -> LiftedMonoidState:
        take_b = b.ver > a.ver  # ties keep a: same (ver, content) by contract

        def pick(x, y):
            tb = take_b.reshape(take_b.shape + (1,) * (x.ndim - 1))
            return jnp.where(tb, y, x)

        return LiftedMonoidState(
            inner=jax.tree.map(pick, a.inner, b.inner),
            ver=jnp.maximum(a.ver, b.ver),
            swept=True,
        )

    def observe(self, state: LiftedMonoidState) -> Any:
        return self.inner.observe(state.inner)

    def total(self, state: LiftedMonoidState) -> Any:
        """Global monoid value: fold every contribution row with the inner
        `+` — the read-side reconciliation (1 logical row out)."""
        from ..harness.dense_replay import fold_rows

        R = state.ver.shape[0]
        return fold_rows(self.inner, state.inner, range(R))


class MonoidContributor:
    """The write/read discipline the lift's contract requires, packaged.

    The (version, content) write-once contract means a writer may apply
    its next op batch ONLY onto its own step-contiguous copy of a row —
    never onto a swept-in peer copy (that copy's version already counts
    ops the writer would re-apply; the result would be a duplicated batch
    riding a legitimate version, exactly the double-count the lift
    exists to prevent, and it wins gossip because its version keeps
    growing). So writes and gossip live in separate states:

    * ``own`` — this member's contributions, built purely by `apply`
      (and `regenerate` after adoption); NEVER merged with remote rows.
    * ``peers`` — everything learned from gossip, merged freely.
    * ``view`` — ``peers ⊔ own``: what to publish, read, and checkpoint.

    This is the G-counter discipline (only increment your own entry;
    merge handles the rest), realized at row granularity."""

    def __init__(self, lift: MonoidLift, n_replicas: int, n_keys: int = 1):
        self.lift = lift
        self.own = lift.init(n_replicas, n_keys)
        self.peers = lift.init(n_replicas, n_keys)

    def apply(self, ops: Any, owned: Sequence[int], **kw: Any) -> Any:
        self.own, extras = self.lift.apply_ops(self.own, ops, owned=owned, **kw)
        return extras

    @property
    def view(self) -> LiftedMonoidState:
        return self.lift.merge(self.peers, self.own)

    def absorb(self, state: LiftedMonoidState) -> None:
        """Merge a swept/fetched state into the gossip side."""
        self.peers = self.lift.merge(self.peers, state)


# --- self-contained row-replace deltas ------------------------------------


def monoid_row_delta(
    lift: MonoidLift, prev: LiftedMonoidState, cur: LiftedMonoidState
) -> Dict[str, Any]:
    """Rows whose version advanced since `prev`, with FULL row payloads.

    Self-contained: applying needs no prior delta (cf. module docstring).
    The version is the authoritative change signal — a row whose content
    changed carries a bumped version by the apply_ops contract."""
    rows = np.nonzero(np.asarray(cur.ver) != np.asarray(prev.ver))[0].astype(np.int32)
    rj = jnp.asarray(rows)
    flat = jax.tree_util.tree_flatten_with_path(cur.inner)[0]
    return {
        "rows": rj,
        "ver": cur.ver[rj],
        "leaves": {jax.tree_util.keystr(p): leaf[rj] for p, leaf in flat},
    }


def apply_monoid_row_delta(
    lift: MonoidLift, state: LiftedMonoidState, delta: Dict[str, Any]
) -> LiftedMonoidState:
    """Replace local rows that the delta carries at a HIGHER version.

    Host-side scatter (gossip path, not the apply hot path), one device
    put — same placement rationale as `delta.expand_delta`."""
    rows = np.asarray(delta["rows"], np.int64)
    dver = np.asarray(delta["ver"])
    local_ver = np.asarray(state.ver).copy()
    take = dver > local_ver[rows]
    if not take.any():
        return state
    sel = rows[take]
    local_ver[sel] = dver[take]
    flat, treedef = jax.tree_util.tree_flatten_with_path(state.inner)
    rebuilt = []
    for p, leaf in flat:
        arr = np.asarray(leaf).copy()
        arr[sel] = np.asarray(delta["leaves"][jax.tree_util.keystr(p)])[take]
        rebuilt.append(jnp.asarray(arr))
    return LiftedMonoidState(
        inner=jax.tree_util.tree_unflatten(treedef, rebuilt),
        ver=jnp.asarray(local_ver.astype(np.int32)),
        # Adopting peer rows via a delta is gossip adoption exactly like
        # merge(): the result must trip apply_ops' write-once guard too.
        swept=True,
    )


def like_monoid_delta(lift: MonoidLift, like_state: LiftedMonoidState) -> Dict[str, Any]:
    """Treedef target for deserializing lifted deltas."""
    z = jnp.zeros((0,), jnp.int32)
    flat = jax.tree_util.tree_flatten_with_path(like_state.inner)[0]
    return {
        "rows": z,
        "ver": z,
        "leaves": {jax.tree_util.keystr(p): z for p, _ in flat},
    }


def monoid_delta_in_bounds(
    lift: MonoidLift, like_state: LiftedMonoidState, delta: Dict[str, Any]
) -> bool:
    """Config/bounds validation of a decoded peer delta (mirrors
    `delta.delta_in_bounds`'s role for the JOIN payloads)."""
    R = int(like_state.ver.shape[0])
    rows = np.asarray(delta.get("rows", None))
    dver = np.asarray(delta.get("ver", None))
    if rows.ndim != 1 or not np.issubdtype(rows.dtype, np.integer):
        return False
    if not np.issubdtype(dver.dtype, np.integer):
        return False
    n = rows.size
    if dver.shape != (n,):
        return False
    if n and (rows.min() < 0 or rows.max() >= R):
        return False
    # Duplicate row indices would make apply's fancy assignment last-write-
    # wins: a crafted [ver 10, ver 3] pair for one row leaves the stale
    # ver-3 payload in place even though each entry individually passes the
    # version guard. Honest publishers never emit duplicates (ADVICE r3 #1).
    if np.unique(rows).size != n:
        return False
    flat = jax.tree_util.tree_flatten_with_path(like_state.inner)[0]
    paths = {jax.tree_util.keystr(p): leaf.shape for p, leaf in flat}
    if set(delta.get("leaves", {})) != set(paths):
        return False
    for p, shape in paths.items():
        if tuple(np.asarray(delta["leaves"][p]).shape) != (n,) + tuple(shape[1:]):
            return False
    return True
