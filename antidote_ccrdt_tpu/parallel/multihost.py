"""Multi-host (multi-process) distribution: the cross-host communication
backend the reference delegates to NCCL/MPI-style infrastructure in other
systems (SURVEY.md §5 "Distributed communication backend").

One JAX process runs per host; `jax.distributed` (gRPC coordination
service + cross-host collectives) takes the role NCCL/MPI plays in the
CUDA world. On TPU pods the collectives ride ICI within a slice and DCN
across slices; in CI the same compiled programs run over multi-process
CPU (Gloo) — the tests spawn real separate OS processes
(tests/test_multihost.py -> scripts/multihost_demo.py).

Layout: the global replica axis factors as (dcn, dc) = (process, local
device), matching the hierarchical reconciliation in `sharded.py` —
lattice all-reduce inside each host first (ICI), then across hosts (DCN),
so the cross-host hop carries one already-locally-merged state per host
rather than every replica.

The public pieces:
* `initialize` — one call per process; after it, `jax.devices()` is the
  global device list and every jitted computation is SPMD across hosts.
* `global_replica_mesh` — ("dcn", "dc", "key") mesh over all processes.
* `state_sharding` / `init_global_state` — place [R, NK, ...] pytrees
  with replicas split (dcn, dc) and instances on key.
* `ops_from_process_local` — each host contributes its own replicas' op
  batches (`jax.make_array_from_process_local_data`); nothing global is
  ever materialized on one host.
* `hierarchical_reconcile` — the inter-DC merge as a two-level lattice
  all-reduce under `shard_map`.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import numpy as np


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    cpu_devices_per_process: Optional[int] = None,
) -> None:
    """Join this process to the distributed runtime. Call before any JAX
    computation. `cpu_devices_per_process` forces the CPU backend with n
    virtual devices (the CI/multi-process-CPU rig); leave None on real TPU
    hosts (device count comes from the topology)."""
    import jax

    if cpu_devices_per_process is not None:
        try:
            jax.config.update("jax_platforms", "cpu")
            try:
                # Cross-process computations on the CPU backend need a real
                # collectives implementation (default "none" raises
                # "Multiprocess computations aren't implemented").
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except (AttributeError, ValueError):
                pass  # newer JAX enables CPU collectives by default
            try:
                jax.config.update("jax_num_cpu_devices", cpu_devices_per_process)
            except AttributeError:
                # Older JAX has no such option; the XLA flag read at the
                # (not yet done) backend init provisions the same devices.
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "")
                    + " --xla_force_host_platform_device_count="
                    + str(cpu_devices_per_process)
                ).strip()
        except RuntimeError as e:
            raise RuntimeError(
                "initialize() must run before the first JAX device op — "
                "import the package, call initialize(), then compute. "
                "(Package import itself is backend-free by design; some "
                "other code touched a device first.)"
            ) from e
    jax.distributed.initialize(
        coordinator_address, num_processes=num_processes, process_id=process_id
    )


def global_replica_mesh(n_key: int = 1):
    """("dcn", "dc", "key") mesh over every device of every process:
    dcn = process (cross-host hops), dc = local device, key = instance
    shards carved out of each host's local devices."""
    import jax
    from jax.sharding import Mesh

    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n_proc = max(d.process_index for d in devs) + 1
    local = len(devs) // n_proc
    assert local % n_key == 0, (local, n_key)
    arr = np.array(devs).reshape(n_proc, local // n_key, n_key)
    return Mesh(arr, ("dcn", "dc", "key"))


def state_sharding(mesh):
    """[R, NK, ...] pytrees: replicas split over (dcn, dc), instances over
    key."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(("dcn", "dc"), "key"))


def init_global_state(init_fn: Callable[[], Any], mesh) -> Any:
    """Build a sharded global state without materializing it on one host:
    `init_fn()` produces the full-shape (cheap, zeros) pytree under jit
    with sharded outputs, so each device only ever holds its shard."""
    import jax

    sh = state_sharding(mesh)
    return jax.jit(init_fn, out_shardings=sh)()


def ops_from_process_local(local_ops: Any, mesh) -> Any:
    """Assemble global [R, B, ...] op batches from each process's
    [R_local, B, ...] contribution. Every process passes the ops for ITS
    replicas only; the result is a global array whose shards live where
    they were produced (no cross-host op shipping)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(("dcn", "dc")))
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(sh, np.asarray(a)),
        local_ops,
    )


def hierarchical_reconcile(state: Any, merge: Callable[[Any, Any], Any], mesh):
    """Inter-DC reconciliation over the (dcn, dc) replica grid: lattice
    all-reduce with the CRDT join inside each host first (ICI), then
    across hosts (DCN). After it, every replica holds the global join.

    `merge` combines two single-replica states ([NK, ...] leaves, no
    replica axis). Requires R == n_dcn * n_dc (one replica per device on
    the replica grid): with more, co-resident replicas would be vmapped
    past each other and never merged — rejected loudly here rather than
    silently under-joining.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from ..utils.jaxcompat import shard_map

    from .dist import lattice_all_reduce

    n_rep = mesh.shape["dcn"] * mesh.shape["dc"]
    R = jax.tree.leaves(state)[0].shape[0]
    if R != n_rep:
        raise ValueError(
            f"hierarchical_reconcile needs R == n_dcn*n_dc ({n_rep}), got "
            f"R={R}: co-resident replicas would never merge"
        )

    spec = P(("dcn", "dc"), "key")
    vmerge = jax.vmap(merge)

    def local(st):
        st = lattice_all_reduce(
            st, "dc", vmerge, mesh.shape["dc"]
        )
        st = lattice_all_reduce(
            st, "dcn", vmerge, mesh.shape["dcn"]
        )
        return st

    return shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )(state)


def process_local_shards(x: Any):
    """The addressable block of a sharded global pytree, as numpy (for
    assertions / host-side reads on each process). Shards are reassembled
    by their index slices, so any sharding layout (replica axis, key axis,
    both) round-trips correctly."""
    import jax

    def one(a):
        shards = list(a.addressable_shards)
        # Local region bounds per dim; missing starts mean unsharded dims.
        starts = [
            min((s.index[d].start or 0) for s in shards)
            for d in range(a.ndim)
        ]
        stops = [
            max(
                (s.index[d].stop if s.index[d].stop is not None else a.shape[d])
                for s in shards
            )
            for d in range(a.ndim)
        ]
        out = np.empty(
            [hi - lo for lo, hi in zip(starts, stops)], dtype=a.dtype
        )
        for s in shards:
            sel = tuple(
                slice(
                    (idx.start or 0) - lo,
                    (idx.stop if idx.stop is not None else dim) - lo,
                )
                for idx, lo, dim in zip(s.index, starts, a.shape)
            )
            out[sel] = np.asarray(s.data)
        return out

    return jax.tree.map(one, x)
