"""Elastic membership: failure detection + join-based gossip anti-entropy.

SURVEY.md §5 marks "failure detection / elastic recovery" absent in the
reference (delegated to the Antidote host). This module is that tier,
built the CRDT way rather than the collective way:

* `parallel.multihost` / `parallel.dist` are the FAST path — SPMD
  collectives over ICI/DCN. Collectives need a fixed, fully-alive world:
  a dead peer hangs the program, and `jax.distributed` cannot shrink the
  world without a restart.
* This module is the FAILURE-TOLERANT path: members exchange whole
  lattice states through a shared store (filesystem here; the transport
  is a trivial read/write interface, so object stores or RPC slot in).
  Because every dense state is a join-semilattice (merge is associative,
  commutative, idempotent — tests/test_properties.py pins the laws),
  gossip needs none of the machinery fragile systems need: a stale
  snapshot merges harmlessly, a duplicated op batch re-applied after
  recovery dedups in the join, and membership can change between any two
  sweeps. Recovery is literally "merge the dead member's last published
  state and keep going".

Pieces:
* `GossipStore` — publish/fetch member snapshots + heartbeats in a
  shared directory (atomic rename writes; `harness.checkpoint` format).
  Since the net/ tier it is the filesystem INSTANCE of the pluggable
  transport surface: `net.transport.GossipNode` over `FsTransport`.
  Every entry point below takes any `GossipNode` — sockets
  (`net.tcp.TcpTransport`) and the deterministic chaos simulator
  (`net.sim.SimTransport`) gossip through the same code paths.
* `alive_members` / `owners` — timeout failure detector + the
  deterministic replica→member assignment everyone recomputes from the
  alive set alone (no coordinator, no consensus: ownership only affects
  WHO applies ops; overlap during a membership transition is safe by
  idempotence).
* `sweep` — fold every peer's latest snapshot into the local state with
  the engine join.

The serial sweep path here is also the contract the overlapped round
pipeline (`parallel/overlap.py`, PR 7) decomposes: its `DeltaPrefetcher`
runs this module's fetch+validate+decode half (`sweep_deltas`' chain
walk, `_resolve_monoid`'s lift discipline) ahead of the round on its own
thread, and the round thread folds the pre-expanded results through
`core.batch_merge`. Convergence is mode-independent — both paths apply
the same joins — which tests/test_overlap.py pins bit-identically.

The real-process drill (3 workers, one killed mid-run, survivors detect,
adopt its replicas, converge to the sequential reference) lives in
scripts/elastic_demo.py + tests/test_elastic.py.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..net.transport import FsTransport, GossipNode
from ..obs import devprof
from ..obs import events as obs_events
from ..obs import profile
from ..obs import spans as obs_spans
from ..utils.metrics import Metrics
from .delta import empty_delta  # noqa: F401 — part of this module's API


# Ingest fast-path knobs. CCRDT_INGEST_COMPACT=0 is the bit-identical
# kill switch: deferred publishes ship immediately, one frame per window,
# exactly the pre-compaction wire trace. CCRDT_INGEST_COALESCE caps how
# many consecutive pending windows a publisher fuses into one frame.
ENV_COMPACT = "CCRDT_INGEST_COMPACT"
ENV_COALESCE = "CCRDT_INGEST_COALESCE"
_FALSE = ("0", "false", "no", "off")


def compact_enabled() -> bool:
    return os.environ.get(ENV_COMPACT, "1").strip().lower() not in _FALSE


def coalesce_max() -> int:
    try:
        v = int(os.environ.get(ENV_COALESCE, "4"))
    except ValueError:
        return 4
    return max(1, v)


class GossipStore(GossipNode):
    """Shared-directory gossip node (the historical name and constructor,
    kept so no caller breaks): `GossipNode` over `net.transport
    .FsTransport`. See net/transport.py for the file layout and the
    timestamp-payload heartbeat format."""

    def __init__(self, root: str, member: str, metrics: Optional[Metrics] = None):
        super().__init__(FsTransport(root, member, metrics=metrics))
        self.root = root


class DeltaPublisher:
    """Publish a member's state as chained deltas with periodic full
    snapshots (the classic delta-CRDT shipping discipline: deltas for
    bandwidth, full states as the resync anchor). Engine-generic via
    `parallel.delta.make_delta`: slot deltas for topk_rmv, entrywise for
    the table engines, self-contained row-replace deltas for MONOID
    engines through the versioned-row lift (`parallel.monoid` — a raw
    monoid engine is auto-wrapped; states must be `LiftedMonoidState`,
    enforced at the first publish)."""

    def __init__(
        self, store: GossipNode, dense: Any, name: Optional[str] = None,
        full_every: int = 8, keep: int = 16,
        lag_source: Optional[Callable[[], float]] = None,
        lag_threshold: float = 8.0,
        lag_full_every: int = 2,
        partitions: Optional[int] = None,
        mesh_plan: Optional[Any] = None,
        pager: Optional[Any] = None,
    ):
        from ..core import serial
        from ..core.behaviour import MergeKind
        from .monoid import MonoidLift

        if getattr(dense, "merge_kind", None) == MergeKind.MONOID:
            dense = MonoidLift(dense)
        self.store = store
        self.dense = dense
        # The header name is the blob's only persisted type record —
        # default to the (possibly lifted) engine's own name so on-disk
        # gossip artifacts identify their engine truthfully.
        self.name = name if name is not None else getattr(
            dense, "type_name", "topk_rmv"
        )
        self.full_every = full_every
        self.keep = keep
        # Lag-driven backpressure: when `lag_source` (typically max
        # lag_ops over obs.lag.LagTracker.report()) says some peer is
        # >= lag_threshold ops behind, anchor cadence tightens to
        # lag_full_every so the laggard resyncs from a RECENT snapshot
        # instead of replaying (or worse, missing) a long delta chain.
        self.lag_source = lag_source
        self.lag_threshold = lag_threshold
        self.lag_full_every = max(1, lag_full_every)
        # Partition plane (core/partition.py): when set, every full
        # anchor ALSO publishes the P+1 digest vector and per-partition
        # psnaps, so peers running `PartialAntiEntropy` can repair only
        # divergent partitions instead of pulling the whole snapshot.
        # None = whole-instance gossip only (the legacy path, and what a
        # mixed-version fleet degrades to).
        self.partitions = partitions
        # mesh/plan.MeshPlan: anchors produce digest slices + psnaps
        # shard by shard (mesh/gossip.py) instead of in one whole-state
        # walk; the published wire blobs are byte-identical, so peers
        # never see the difference. None = unsharded production.
        self.mesh_plan = mesh_plan
        # core/pager.PartitionPager: under out-of-core paging the device
        # state is only the HOT slice of the logical state. Anchors then
        # publish `pager.full_state` (the logical join) so whole-snapshot
        # consumers see no hole, and the partition surface serves cold
        # digests/psnaps straight from the pager's stored CCPT blobs.
        # Deltas are untouched: they are cut device-side, where cold
        # slices never change between publishes. None = all-resident.
        self.pager = pager
        self.seq = -1
        self._prev: Any = None
        self._serial = serial
        # Wire-window staging (ingest fast path): `publish(..., defer=
        # True)` parks delta windows here instead of shipping each one;
        # `flush_wire` fuses them into ONE range frame [lo..hi] via
        # `ops.compaction.coalesce_deltas` (falling back to re-cutting
        # the interval delta against `_wire_prev`, the last state that
        # actually reached the wire — exact for every engine). Entries
        # are (seq, delta, blob-or-None).
        self._staged: List[Tuple[int, Any, Optional[bytes]]] = []
        self._wire_prev: Any = None
        self._last_state: Any = None
        # encode_delta stash: (seq, is_full) frozen so the publish that
        # consumes a pre-cut blob takes the SAME anchor/pressure branch
        # the encode did (a lag probe flipping between the two calls
        # would otherwise ship a blob cut for the wrong branch).
        self._next_plan: Optional[Tuple[int, bool]] = None
        # Serve-plane hook: called as on_publish(state, seq) after every
        # publish, the natural swap point for a read replica — the state
        # just shipped is exactly what peers will converge toward, so
        # serving it keeps reads within one round of the write frontier.
        self.on_publish: Optional[Callable[[Any, int], None]] = None

    def _branch(self, seq: int) -> bool:
        """True = `seq` publishes a full anchor. Evaluates (and counts)
        the lag-pressure probe, so call once per seq — `encode_delta`
        freezes its answer in `_next_plan` for the matching publish."""
        full_every = self.full_every
        pressured = False
        if self.lag_source is not None:
            try:
                pressured = float(self.lag_source()) >= self.lag_threshold
            except Exception:
                pressured = False  # a broken probe must not stop publishing
        if pressured and self.lag_full_every < full_every:
            full_every = self.lag_full_every
            self.store.metrics.count("net.lag_anchor_cuts")
        return self._prev is None or seq % full_every == 0

    def encode_delta(self, state: Any) -> Optional[Dict[str, Any]]:
        """Pre-cut the NEXT publish's delta so callers can reuse ONE
        join-decomposed delta for both the WAL record and the gossip
        blob (`wal.log_step(..., delta=, blob=)` then
        `publish(state, encoded=...)`) instead of extracting it twice.
        Returns None when the next publish is a full anchor (anchors
        ship whole snapshots; the WAL then cuts its own delta)."""
        from .delta import make_delta

        seq = self.seq + 1
        is_full = self._branch(seq)
        self._next_plan = (seq, is_full)
        if is_full:
            return None
        if obs_spans.ACTIVE:
            with obs_spans.span(
                "round.delta_encode", origin=self.store.member, dseq=seq
            ):
                delta = make_delta(self.dense, self._prev, state)
                blob = self._serial.dumps_dense(f"{self.name}_delta", delta)
        else:
            delta = make_delta(self.dense, self._prev, state)
            blob = self._serial.dumps_dense(f"{self.name}_delta", delta)
        return {"seq": seq, "delta": delta, "blob": blob}

    def publish(
        self, state: Any, encoded: Optional[Dict[str, Any]] = None,
        defer: bool = False,
    ) -> Dict[str, Any]:
        """Publish one window. With `defer=True` (and the ingest fast
        path enabled) a delta window is STAGED instead of shipped; the
        wire frame goes out when `coalesce_max()` windows are pending,
        at the next non-deferred publish, at an anchor (which flushes
        the staged tail before it lands), or at an explicit
        `flush_wire()` — whichever comes first. Anchors are never
        deferred."""
        from .delta import make_delta

        from .monoid import LiftedMonoidState, MonoidLift

        if isinstance(self.dense, MonoidLift) and not isinstance(
            state, LiftedMonoidState
        ):
            raise TypeError(
                "DeltaPublisher.publish: monoid gossip needs versioned "
                "rows — build the state with MonoidLift(engine).init(...) "
                "(parallel/monoid.py)"
            )
        self.seq += 1
        if self._next_plan is not None and self._next_plan[0] == self.seq:
            is_full = self._next_plan[1]
            self._next_plan = None
        else:
            self._next_plan = None
            is_full = self._branch(self.seq)
        if is_full:
            # Ship any staged-but-unshipped windows BEFORE the anchor
            # lands. Discarding them (the anchor IS their join, at a
            # higher seq) looks like a free optimization, but with the
            # default coalesce cap (4) >= the drills' full_every (4)
            # the cap can never fill inside an anchor interval — every
            # window would be superseded and NO delta ever reaches the
            # wire: peers resync through full anchors only and the
            # fast path goes dark. Flushing keeps the chain continuous;
            # a peer that already swept the anchor skips the older
            # frame seqs by cursor, so the join is unchanged.
            self.flush_wire()
            # Under paging the anchor must carry the LOGICAL state —
            # a device-only snapshot would publish identity holes where
            # the cold partitions live.
            pub_state = state
            if self.pager is not None and self.pager.has_cold():
                pub_state = self.pager.full_state(state)
            if obs_spans.ACTIVE:
                # Full-snapshot anchor: serialize + hand to the medium.
                with obs_spans.span("round.snapshot", seq=self.seq):
                    self.store.publish(self.name, pub_state, self.seq)
            else:
                self.store.publish(self.name, pub_state, self.seq)
            if self.partitions:
                # Partition artifacts ride the anchor cadence: the full
                # snapshot stays published (legacy peers and the
                # psnap-exhausted fallback read it), digests + changed
                # psnaps go alongside.
                self.store.publish_partitioned(
                    self.name, state, self.seq, self.dense, self.partitions,
                    plan=self.mesh_plan, pager=self.pager,
                )
            self._wire_prev = state
            self._last_state = state
            kind, nbytes = "full", -1
        else:
            staging = defer and compact_enabled()
            if (
                encoded is not None
                and encoded.get("seq") == self.seq
                and encoded.get("blob") is not None
            ):
                # Pre-cut by encode_delta (same _prev, same seq): the
                # extraction cost was already paid — and already
                # attributed to round.delta_encode — there.
                delta, blob = encoded.get("delta"), encoded["blob"]
            else:
                if obs_spans.ACTIVE:
                    with obs_spans.span(
                        "round.delta_encode", origin=self.store.member,
                        dseq=self.seq,
                    ):
                        delta = make_delta(self.dense, self._prev, state)
                        # A deferred window's bytes may never ship (the
                        # coalesced frame re-serializes) — skip the dump
                        # until flush decides.
                        blob = (
                            None if staging else self._serial.dumps_dense(
                                f"{self.name}_delta", delta
                            )
                        )
                else:
                    delta = make_delta(self.dense, self._prev, state)
                    blob = (
                        None if staging else self._serial.dumps_dense(
                            f"{self.name}_delta", delta
                        )
                    )
            self._staged.append((self.seq, delta, blob))
            self._last_state = state
            if staging and len(self._staged) < coalesce_max():
                kind, nbytes = "staged", 0
            else:
                shipped = self.flush_wire()
                kind, nbytes = "delta", shipped["nbytes"]
        self._prev = state
        if self.on_publish is not None:
            try:
                self.on_publish(state, self.seq)
            except Exception:
                # The read plane must never stall the write plane.
                self.store.metrics.count("serve.swap_errors")
        return {"kind": kind, "seq": self.seq, "nbytes": nbytes}

    @property
    def staged_windows(self) -> int:
        return len(self._staged)

    def flush_wire(self) -> Optional[Dict[str, Any]]:
        """Ship every staged window as ONE range frame [lo..hi] (None
        when nothing is pending). Multi-window frames fuse through
        `ops.compaction.coalesce_deltas`; flavors without a coalesce
        kernel (lifted-monoid row deltas) re-cut the interval delta
        against `_wire_prev` — the last state that reached the wire —
        which is exact for every engine. Either way the frame joins to
        the bit-identical state the chained per-window frames would."""
        if not self._staged:
            return None
        from ..ops.compaction import coalesce_deltas
        from .delta import make_delta

        lo = self._staged[0][0]
        hi = self._staged[-1][0]
        if len(self._staged) == 1:
            delta, blob = self._staged[0][1], self._staged[0][2]
            if blob is None:
                if obs_spans.ACTIVE:
                    with obs_spans.span(
                        "round.delta_encode", origin=self.store.member,
                        dseq=hi,
                    ):
                        blob = self._serial.dumps_dense(
                            f"{self.name}_delta", delta
                        )
                else:
                    blob = self._serial.dumps_dense(
                        f"{self.name}_delta", delta
                    )
        else:
            def _fuse() -> bytes:
                fused = coalesce_deltas(
                    self.dense, [d for _, d, _ in self._staged]
                )
                if fused is None:
                    fused = make_delta(
                        self.dense, self._wire_prev, self._last_state
                    )
                return self._serial.dumps_dense(f"{self.name}_delta", fused)

            if obs_spans.ACTIVE:
                with obs_spans.span(
                    "round.delta_encode", origin=self.store.member,
                    dseq=hi, lo=lo, via="coalesce",
                ):
                    blob = _fuse()
            else:
                blob = _fuse()
        self.store.publish_delta(blob, hi, keep=self.keep, lo=lo)
        self._staged.clear()
        self._wire_prev = self._last_state
        return {"kind": "delta", "seq": hi, "lo": lo, "nbytes": len(blob)}


class PartialAntiEntropy:
    """Partition-granular resync (the tentpole of the partition plane):
    instead of pulling a peer's whole snapshot on a delta-chain gap,
    compare `P+1`-entry digest vectors (`core.partition.state_digests`)
    and fetch psnaps for **only the divergent partitions**.

    Outcome ladder per (member, gap):
    1. vectors fully agree → advance the cursor to the digest seq with
       ZERO fetches (the gap was bandwidth already paid via another
       route — nothing to transfer at all);
    2. some partitions diverge → `request_psnaps` + fetch + join each;
       a partition counts repaired when the post-merge digest matches
       the peer's OR the psnap's own seq has caught up to the digest seq
       (a stored psnap's seq is the last anchor at which that partition
       changed, so "older but matching" is complete, not stale);
    3. psnaps missing / still divergent after `max_tries` sweeps →
       report unhandled, and `sweep_deltas` falls back to the legacy
       whole-snapshot fetch (also the mixed-version-fleet path: a legacy
       peer publishes no digests, so step 1 bails immediately).

    Counters: `net.partition_resyncs` (completed partial repairs),
    `part.divergent` gauge (size of the last divergence set), and
    `net.psnap_wasted` — fetches for a partition whose digests already
    agreed. By construction this stays 0; scripts/chaos_gate.py fails
    the build if it ever isn't."""

    def __init__(
        self, store: GossipNode, partitions: Optional[int] = None,
        max_tries: int = 3, watchdog: Optional[Any] = None,
        mesh_plan: Optional[Any] = None, pager: Optional[Any] = None,
    ):
        from ..core import partition as pt

        self.store = store
        self.partitions = partitions if partitions else pt.n_partitions()
        self.max_tries = max(1, max_tries)
        self._pt = pt
        # mesh/plan.MeshPlan: divergent-partition fetches are grouped by
        # owning key shard (mesh/gossip.group_parts_by_shard) so a
        # repair pulls shard-local psnap slices and stitches them back
        # together, billing `mesh.cross_slice_fetches` / `.cross_slice_
        # bytes`. None = the flat fetch order (unsharded behavior).
        self.mesh_plan = mesh_plan
        # member -> consecutive incomplete partial-resync attempts; reset
        # on completion, tripped into full-snap fallback at max_tries.
        self._tries: Dict[str, int] = {}
        # Optional obs.audit.DivergenceWatchdog: every digest exchange
        # below feeds it (observe_peer), and applied psnaps reset its
        # wedge clock (note_repair_progress) — this resync loop IS the
        # repair whose absence the wedged-divergence alarm detects.
        self.watchdog = watchdog
        # core/pager.PartitionPager: digest vectors come from
        # `pager.digest_vector` (device entries for hot partitions,
        # cached CCPT digests for cold) and fetched psnaps targeting
        # cold partitions fold host-side instead of hydrating — partial
        # anti-entropy never blocks on a page-in. None = all-resident.
        self.pager = pager

    def _own_vec(self, state: Any) -> Any:
        if self.pager is not None and self.pager.has_cold():
            return self.pager.digest_vector(state)
        return self._pt.state_digests(state, self.partitions)

    def try_resync(
        self, member: str, dense: Any, state: Any, cur: int
    ) -> Tuple[Any, int, bool]:
        """(state, cursor, handled). handled=False → caller should run
        the whole-snapshot path."""
        from .delta import apply_any_delta, delta_in_bounds, like_delta_for

        pt, P = self._pt, self.partitions
        got = self.store.fetch_digests(member)
        if got is None:
            return state, cur, False  # legacy peer / torn blob
        dig_seq, peer_vec = got
        if dig_seq <= cur or len(peer_vec) != P + 1:
            # Digest older than our cursor (the snap outran it) or a
            # fleet disagreeing on P: partial resync can't certify
            # anything — use the full snapshot.
            return state, cur, False
        own_vec = self._own_vec(state)
        div = pt.divergent_parts(own_vec, peer_vec)
        self.store.metrics.set("part.divergent", float(len(div)))
        if self.watchdog is not None:
            self.watchdog.observe_peer(member, own_vec, peer_vec, seq=dig_seq)
        if not div:
            # Full agreement: the peer's anchor adds nothing we lack.
            self.store.metrics.count("net.partition_agree_advances")
            obs_events.emit(
                "psnap.resync", origin=member, parts=[], dig_seq=dig_seq,
                fetched=0,
            )
            self._tries.pop(member, None)
            return state, max(cur, dig_seq), True
        # Wasted-resync guard (chaos_gate's detector): only divergent
        # partitions may be fetched. Anything else would be billed here.
        fetch_parts = []
        for p in div:
            if own_vec[p] == peer_vec[p]:
                self.store.metrics.count("net.psnap_wasted")
                continue
            fetch_parts.append(p)
        if self.mesh_plan is not None:
            # Shard-local slices: fetch in owning-shard order, one
            # shard's partitions at a time, and stitch the repairs back
            # together (the join is order-free, so grouping is free).
            from ..mesh import gossip as mesh_gossip

            groups = mesh_gossip.group_parts_by_shard(
                self.mesh_plan, fetch_parts
            )
            fetch_parts = [p for _s, ps in groups for p in ps]
        self.store.request_psnaps(member, fetch_parts)
        like = like_delta_for(dense, state)
        repaired_by_seq = set()
        fetched = 0
        bytes_before = self.store.metrics.counters.get("net.psnap_bytes", 0.0)
        for p in fetch_parts:
            r = self.store.fetch_psnap(
                member, p, like,
                validate=lambda d: delta_in_bounds(dense, state, d),
            )
            if r is None:
                continue  # not served yet (push media) — next sweep
            ps_seq, payload = r
            try:
                if self.pager is not None:
                    # Cold-targeting psnaps fold host-side (or queue);
                    # hot ones join on device — never a forced page-in.
                    state = self.pager.apply_delta(state, payload)
                else:
                    state = apply_any_delta(dense, state, payload)
            except Exception:  # noqa: BLE001 — total, same as sweep
                continue
            fetched += 1
            if self.mesh_plan is not None:
                self.store.metrics.count("mesh.cross_slice_fetches")
            if ps_seq >= dig_seq:
                repaired_by_seq.add(p)
        if self.mesh_plan is not None and fetched:
            bytes_after = self.store.metrics.counters.get(
                "net.psnap_bytes", 0.0
            )
            self.store.metrics.count(
                "mesh.cross_slice_bytes", float(bytes_after - bytes_before)
            )
        post_vec = self._own_vec(state)
        outstanding = [
            p for p in fetch_parts
            if post_vec[p] != peer_vec[p] and p not in repaired_by_seq
        ]
        if fetched and self.watchdog is not None:
            self.watchdog.note_repair_progress(member)
        if not outstanding:
            self.store.metrics.count("net.partition_resyncs")
            obs_events.emit(
                "psnap.resync", origin=member, parts=list(fetch_parts),
                dig_seq=dig_seq, fetched=fetched,
            )
            self._tries.pop(member, None)
            return state, max(cur, dig_seq), True
        tries = self._tries.get(member, 0) + 1
        self._tries[member] = tries
        if tries >= self.max_tries:
            # Residual divergence partial resync can't close (e.g. the
            # peer pruned psnaps, or P mismatch upstream): give up and
            # let the whole snapshot repair everything.
            self._tries.pop(member, None)
            return state, cur, False
        # In progress: psnaps requested, replies in flight. Skip the
        # full fetch this sweep; joins already applied are kept (they
        # are monotone — never wrong, at worst incomplete).
        return state, cur, True


def sweep_deltas(
    store: GossipNode, dense: Any, state: Any, cursors: Dict[str, int],
    partial: Optional[PartialAntiEntropy] = None,
    pager: Optional[Any] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """Delta-aware sweep: per peer, chain contiguous deltas from the
    cursor; on a gap (pruned, torn, or never-seen member) resync from the
    peer's full snapshot and continue chaining. `cursors` maps member ->
    highest seq applied and is updated in place. Applying a full snapshot
    after deltas (or twice) is harmless — everything is a join.

    With `partial` (a `PartialAntiEntropy`), the gap branch first tries
    partition-granular repair — digest-vector compare, then psnaps for
    only the divergent partitions — and falls back to the whole snapshot
    when the peer has no partition surface or the partial repair stalls.

    With `pager` (a `core.pager.PartitionPager`), deltas and snapshots
    targeting cold partitions fold host-side through the pager instead
    of joining on device — the sweep never forces a hydration."""
    from .delta import apply_any_delta, delta_in_bounds, like_delta_for

    dense, state = _resolve_monoid(dense, state, "sweep_deltas")
    like_delta = like_delta_for(dense, state)
    stats = {"deltas": 0, "fulls": 0, "skipped": 0}

    def _apply(st: Any, delta: Any) -> Any:
        if pager is not None:
            return pager.apply_delta(st, delta)
        return apply_any_delta(dense, st, delta)

    def chain(member: str, cur: int) -> int:
        nonlocal state, stats
        avail = sorted(store.delta_seqs(member))
        while True:
            # Frames are stored under their HIGH seq; a range frame
            # [lo..hi] is applicable iff lo <= cur+1 (overlapping
            # coverage below the cursor is harmless — every gossip
            # delta joins idempotently). Legacy frames are the lo==hi
            # degenerate case, so this loop subsumes the old cur+1 walk.
            nxt = next((s for s in avail if s > cur), None)
            if nxt is None:
                break
            got = store.fetch_delta_framed(
                member, nxt, like_delta,
                validate=lambda d: delta_in_bounds(dense, state, d),
            )
            if got is None:
                break  # torn/mismatched write: retry (or resync) next sweep
            lo, hi, delta = got
            if lo > cur + 1:
                break  # real gap below the frame → anchor resync path
            # Same total-failure policy as fetch/fetch_delta: a decodable-
            # but-malformed delta that slips past delta_in_bounds must not
            # crash the gossip loop — break the chain and resync next sweep.
            try:
                tok = (
                    obs_spans.begin(
                        "round.delta_apply", origin=member, dseq=hi, lo=lo
                    )
                    if obs_spans.ACTIVE
                    else None
                )
                try:
                    if profile.ACTIVE or devprof.ACTIVE:
                        with profile.dispatch("elastic.delta_apply", operands=(delta,)):
                            state = _apply(state, delta)
                    else:
                        state = _apply(state, delta)
                finally:
                    obs_spans.end(tok)
            except Exception:  # noqa: BLE001 — deliberately total
                stats["skipped"] += 1
                break
            stats["deltas"] += 1
            cur = hi
            # Terminal stage of the delta trace: (origin, dseq) merged
            # into THIS member's state. `lo` rides along so the audit
            # accepts the range jump as chained, not a gap-skip.
            obs_events.emit("delta.apply", origin=member, dseq=cur, lo=lo)
        return cur

    for m in sorted(set(store.snapshot_members()) | set(store.delta_members())):
        if m == store.member:
            continue
        cur = cursors.get(m, -1)
        cur = chain(m, cur)
        snap_seq = store.snapshot_seq(m)
        if snap_seq is not None and snap_seq > cur:
            if partial is not None:
                state, cur2, handled = partial.try_resync(m, dense, state, cur)
                if handled:
                    if cur2 > cur:
                        cur = chain(m, cur2)
                        stats["partials"] = stats.get("partials", 0) + 1
                    cursors[m] = cur
                    continue
            got = store.fetch(m, state, dense=dense)
            if got is None:
                stats["skipped"] += 1
            else:
                _seq, peer = got
                try:
                    tok = (
                        obs_spans.begin(
                            "round.delta_apply", origin=m, step=_seq,
                            via="snap",
                        )
                        if obs_spans.ACTIVE
                        else None
                    )
                    try:
                        if pager is not None and pager.has_cold():
                            # Cold slices of the peer fold host-side;
                            # the device merge sees only the hot rest.
                            peer = pager.absorb_peer(peer)
                        if profile.ACTIVE or devprof.ACTIVE:
                            with profile.dispatch(
                                "elastic.snap_merge", fn=dense.merge, operands=(peer,)
                            ):
                                state = dense.merge(state, peer)
                        else:
                            state = dense.merge(state, peer)
                    finally:
                        obs_spans.end(tok)
                except Exception:  # noqa: BLE001 — deliberately total
                    stats["skipped"] += 1
                else:
                    stats["fulls"] += 1
                    obs_events.emit("snap.apply", origin=m, step=_seq)
                    cur = max(cur, _seq)
                    cur = chain(m, cur)
        cursors[m] = cur
    return state, stats


def owners(alive: List[str], n_replicas: int) -> Dict[int, str]:
    """Deterministic replica→member assignment from the alive set alone:
    replica r belongs to alive[r % len(alive)]. Every member computes this
    locally; during a membership transition two members may briefly both
    own a replica and apply the same deterministic op stream — harmless,
    the join dedups (idempotence is what makes coordination unnecessary)."""
    alive = sorted(alive)
    if not alive:
        return {}
    return {r: alive[r % len(alive)] for r in range(n_replicas)}


def my_replicas(store: GossipNode, n_replicas: int, timeout_s: float) -> List[int]:
    own = owners(store.alive_members(timeout_s), n_replicas)
    return [r for r, m in own.items() if m == store.member]


def _resolve_monoid(dense: Any, state: Any, where: str) -> Tuple[Any, Any]:
    """Gossip entry points speak the JOIN algebra. MONOID engines enter
    through the versioned-row lift (`parallel.monoid.MonoidLift`): handed
    a raw monoid engine, auto-wrap it — but the STATE must already carry
    row versions (they are real protocol information only the writer can
    produce), so a raw monoid state is a usage error, not something to
    paper over."""
    from ..core.behaviour import MergeKind
    from .monoid import LiftedMonoidState, MonoidLift

    if getattr(dense, "merge_kind", None) == MergeKind.MONOID:
        dense = MonoidLift(dense)
    if isinstance(dense, MonoidLift) and not isinstance(state, LiftedMonoidState):
        raise TypeError(
            f"{where}: monoid gossip needs versioned rows — build the "
            "state with MonoidLift(engine).init(...) and apply ops "
            "through the lift (parallel/monoid.py)"
        )
    return dense, state


def sweep(
    store: GossipNode, dense: Any, state: Any, pager: Optional[Any] = None
) -> Tuple[Any, int]:
    """Fold every peer's latest snapshot into `state` with the engine
    join. Returns (state, n_merged). Self's snapshot is skipped (already
    reflected); stale or concurrent publishes are safe by idempotence
    (MONOID engines ride the versioned-row lift, where row-replace is
    the idempotent join — `parallel.monoid`)."""
    dense, state = _resolve_monoid(dense, state, "sweep")
    n = 0
    for m in store.snapshot_members():
        if m == store.member:
            continue
        got = store.fetch(m, state, dense=dense)
        if got is None:
            continue
        _step, peer = got
        if pager is not None and pager.has_cold():
            peer = pager.absorb_peer(peer)
        tok = (
            obs_spans.begin("round.delta_apply", origin=m, step=_step, via="sweep")
            if obs_spans.ACTIVE
            else None
        )
        try:
            if profile.ACTIVE or devprof.ACTIVE:
                with profile.dispatch(
                    "elastic.sweep_merge", fn=dense.merge, operands=(peer,)
                ):
                    state = dense.merge(state, peer)
            else:
                state = dense.merge(state, peer)
        finally:
            obs_spans.end(tok)
        # Visible to the replay certifier: a full-snapshot fold covers the
        # origin's stream through _step (obs/audit.py reconcile_op_counts).
        obs_events.emit("snap.apply", origin=m, step=_step, via="sweep")
        n += 1
    return state, n
