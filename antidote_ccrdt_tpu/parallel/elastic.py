"""Elastic membership: failure detection + join-based gossip anti-entropy.

SURVEY.md §5 marks "failure detection / elastic recovery" absent in the
reference (delegated to the Antidote host). This module is that tier,
built the CRDT way rather than the collective way:

* `parallel.multihost` / `parallel.dist` are the FAST path — SPMD
  collectives over ICI/DCN. Collectives need a fixed, fully-alive world:
  a dead peer hangs the program, and `jax.distributed` cannot shrink the
  world without a restart.
* This module is the FAILURE-TOLERANT path: members exchange whole
  lattice states through a shared store (filesystem here; the transport
  is a trivial read/write interface, so object stores or RPC slot in).
  Because every dense state is a join-semilattice (merge is associative,
  commutative, idempotent — tests/test_properties.py pins the laws),
  gossip needs none of the machinery fragile systems need: a stale
  snapshot merges harmlessly, a duplicated op batch re-applied after
  recovery dedups in the join, and membership can change between any two
  sweeps. Recovery is literally "merge the dead member's last published
  state and keep going".

Pieces:
* `GossipStore` — publish/fetch member snapshots + mtime heartbeats in a
  shared directory (atomic rename writes; `harness.checkpoint` format).
* `alive_members` / `owners` — timeout failure detector + the
  deterministic replica→member assignment everyone recomputes from the
  alive set alone (no coordinator, no consensus: ownership only affects
  WHO applies ops; overlap during a membership transition is safe by
  idempotence).
* `sweep` — fold every peer's latest snapshot into the local state with
  the engine join.

The real-process drill (3 workers, one killed mid-run, survivors detect,
adopt its replicas, converge to the sequential reference) lives in
scripts/elastic_demo.py + tests/test_elastic.py.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..harness.checkpoint import load_dense_checkpoint, save_dense_checkpoint


class GossipStore:
    """Shared-directory snapshot exchange with heartbeat files.

    Layout: `<root>/snap-<member>` (latest lattice state, atomic replace)
    and `<root>/hb-<member>` (empty file; mtime = last heartbeat). One
    writer per member id; any number of readers."""

    def __init__(self, root: str, member: str):
        self.root = root
        self.member = member
        os.makedirs(root, exist_ok=True)
        self.heartbeat()

    # -- liveness ----------------------------------------------------------

    def heartbeat(self) -> None:
        p = os.path.join(self.root, f"hb-{self.member}")
        with open(p, "a"):
            os.utime(p, None)

    def members(self) -> List[str]:
        return sorted(
            f[3:] for f in os.listdir(self.root) if f.startswith("hb-")
        )

    def alive_members(self, timeout_s: float) -> List[str]:
        """Members whose heartbeat is fresher than `timeout_s`. Always
        includes self (a member never suspects itself)."""
        now = time.time()
        out = []
        for m in self.members():
            if m == self.member:
                out.append(m)
                continue
            try:
                age = now - os.path.getmtime(os.path.join(self.root, f"hb-{m}"))
            except OSError:
                continue
            if age <= timeout_s:
                out.append(m)
        return sorted(out)

    # -- snapshots ---------------------------------------------------------

    def publish(self, name: str, state: Any, step: int) -> None:
        """Atomically publish this member's state at `step` (and beat)."""
        save_dense_checkpoint(
            os.path.join(self.root, f"snap-{self.member}"), name, state, step
        )
        self.heartbeat()

    def fetch(
        self, member: str, like: Any, dense: Any = None
    ) -> Optional[Tuple[int, Any]]:
        """Latest (step, state) published by `member`, or None. ANY decode
        or validation failure reads as None — torn concurrent writes raise
        struct.error/BadZipFile (not OSError/ValueError), and a peer
        publishing under a mismatched engine config must be skipped, not
        crash the gossip loop: join-based gossip never needs any single
        fetch to succeed, the next sweep retries."""
        path = os.path.join(self.root, f"snap-{member}")
        try:
            step, _name, state = load_dense_checkpoint(path, like, dense=dense)
        except Exception:  # noqa: BLE001 — deliberately total, see docstring
            return None
        return step, state

    def snapshot_members(self) -> List[str]:
        return sorted(
            f[5:]
            for f in os.listdir(self.root)
            if f.startswith("snap-") and not f.endswith(".tmp")
        )


def owners(alive: List[str], n_replicas: int) -> Dict[int, str]:
    """Deterministic replica→member assignment from the alive set alone:
    replica r belongs to alive[r % len(alive)]. Every member computes this
    locally; during a membership transition two members may briefly both
    own a replica and apply the same deterministic op stream — harmless,
    the join dedups (idempotence is what makes coordination unnecessary)."""
    alive = sorted(alive)
    if not alive:
        return {}
    return {r: alive[r % len(alive)] for r in range(n_replicas)}


def my_replicas(store: GossipStore, n_replicas: int, timeout_s: float) -> List[int]:
    own = owners(store.alive_members(timeout_s), n_replicas)
    return [r for r, m in own.items() if m == store.member]


def sweep(store: GossipStore, dense: Any, state: Any) -> Tuple[Any, int]:
    """Fold every peer's latest snapshot into `state` with the engine
    join. Returns (state, n_merged). Self's snapshot is skipped (already
    reflected); stale or concurrent publishes are safe by idempotence."""
    n = 0
    for m in store.snapshot_members():
        if m == store.member:
            continue
        got = store.fetch(m, state, dense=dense)
        if got is None:
            continue
        _step, peer = got
        state = dense.merge(state, peer)
        n += 1
    return state, n
