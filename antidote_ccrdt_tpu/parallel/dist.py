"""Distribution layer: replica/key sharding over a jax.sharding.Mesh with
collective merges riding ICI.

The reference's distribution model is op-based geo-replication provided by
an absent host (SURVEY.md §2 "Parallelism" checklist: no DP/TP/PP/SP/EP, no
NCCL/MPI — only the delivery contract). The TPU-native equivalent built
here:

* **dc axis** — simulated DCs/replicas are data-parallel shards; the
  "inter-DC exchange" is a real XLA collective over the mesh instead of a
  host shipping op logs.
* **key axis** — the scaling axis analogous to sequence parallelism in ML
  workloads (SURVEY.md §5): the CRDT instance grid (and for huge instances
  the element-id space) shards across devices; instances are independent so
  this axis needs no collectives.

Merges use `lattice_all_reduce`: a recursive-doubling (hypercube) all-reduce
whose combiner is the CRDT's own merge. For MONOID types (+) this is what
`psum` does internally; for JOIN types the combiner is the lattice join
(slot-sort + vc max), which psum cannot express — so the primitive is built
from `ppermute` exchanges: log2(n) rounds, each pairing devices across one
hypercube dimension, exactly how one would hand-schedule it over ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_dc: int, n_key: int = 1, devices=None) -> Mesh:
    """A (dc, key) mesh: replicas × instance-shards."""
    devices = devices if devices is not None else jax.devices()
    n = n_dc * n_key
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
    return Mesh(np.asarray(devices[:n]).reshape(n_dc, n_key), ("dc", "key"))


def lattice_all_reduce(x: Any, axis_name: str, merge: Callable[[Any, Any], Any], axis_size: int):
    """All-reduce a pytree over a mesh axis with an arbitrary associative,
    commutative combiner (the CRDT merge).

    Recursive doubling: in round k each device exchanges its accumulator
    with its partner across hypercube dimension k and merges, so after
    log2(n) rounds every device holds the full merge. Non-power-of-two axes
    fall back to gather-reduce (correct, O(n) memory)."""
    if axis_size & (axis_size - 1) != 0:
        return all_gather_reduce(x, axis_name, merge, axis_size)
    k = 1
    while k < axis_size:
        perm = [(i, i ^ k) for i in range(axis_size)]
        other = jax.tree.map(lambda a: lax.ppermute(a, axis_name, perm), x)
        x = merge(x, other)
        k *= 2
    return x


def all_gather_reduce(x: Any, axis_name: str, merge: Callable[[Any, Any], Any], axis_size: int):
    """Fallback all-reduce for non-power-of-two axes: gather every shard and
    fold the merge locally. O(n) memory — prefer lattice_all_reduce."""
    gathered = jax.tree.map(lambda a: lax.all_gather(a, axis_name), x)

    def take(i):
        return jax.tree.map(lambda a: a[i], gathered)

    acc = take(0)
    for i in range(1, axis_size):
        acc = merge(acc, take(i))
    return acc


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """State pytrees [R, NK, ...]: replicas on 'dc', instances on 'key'."""
    return NamedSharding(mesh, P("dc", "key"))


def shard_state(state: Any, mesh: Mesh) -> Any:
    """Place a [R, NK, ...] state pytree onto the mesh (dc × key)."""
    sh = replica_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), state)


def shard_ops(ops: Any, mesh: Mesh) -> Any:
    """Op batches are [R, B...]: shard replicas on 'dc', replicate over 'key'
    (each key-shard filters by instance index inside the kernel)."""
    sh = NamedSharding(mesh, P("dc"))
    return jax.tree.map(lambda a: jax.device_put(a, sh), ops)
