"""Overlapped round pipeline: host I/O off the round thread (PR 7).

The span plane (obs/spans.py, PR 6) measured what BENCH_r02-r05 kept
attributing: the e2e gossip round runs ~2.7x the windowed device compute
because WAL append, delta encode/decode, and gossip I/O sit SERIALLY on
the same thread as device dispatch. This module is the restructure —
three mechanisms, each mapped onto the pieces the rest of the repo
already has:

1. **Double-buffered device state.** Device merges go through cached
   jitted entry points with DONATED arguments
   (`core.batch_merge.merge_slots`): the incoming side (a freshly
   expanded peer delta the pipeline owns) aliases its buffers into the
   output, so window N+1's merge dispatches while window N's result is
   still being read back/encoded on the host stage.
   `jax.block_until_ready` happens only at the publish boundary — inside
   the host stage's publish task, off the round thread.

2. **Async host stages.** One `HostStage` worker thread owns WAL append,
   delta encode, and gossip send. Its bounded FIFO queue is the ordering
   guarantee: append(step) is submitted before publish(step), so
   durability still precedes visibility (the PR-2 write-ahead contract)
   — just not on the round thread. A full queue blocks the submitter
   (backpressure, billed `overlap.stalls`); a task exception fail-stops
   the stage (re-raised at the next submit/drain — async must not
   swallow durability failures). Inbound, a `DeltaPrefetcher` thread
   runs the fetch+decode half of `elastic.sweep_deltas` ahead of the
   round, pre-expanding topk_rmv deltas to mergeable full states
   (`delta.expand_delta` — host scatter cost paid off-thread) into a
   bounded `ApplyQueue`.

3. **Multi-window batched dispatch.** When the apply queue holds >=2
   mergeable windows, `drain_into` folds them — current state riding
   along — in one `core.batch_merge.fold_states` call (log2 N batched
   dispatches) instead of one dispatch per window.

PR 11 adds the durability half of the bargain: `CommitCoalescer` runs
group commit ON the host stage — WAL appends stage (write, no fsync) and
the publish-boundary task, FIFO-after every append it covers, commits
the whole batch with one fsync per dirty segment stream (see
harness/wal.py for the three durability modes and the async watermark).

Overflow policy (`ApplyQueue`): drop-oldest-delta-keep-anchor, mirroring
`net/tcp.py`'s send-queue shed. Dropping delta seq k breaks the chained
contiguity obligation for that member, so the shed also drops its later
queued deltas, records a per-member HOLE (`overlap.dropped_deltas`
billed per drop), and refuses further deltas from that member until the
prefetcher lands a full-snapshot anchor with seq >= the hole — the
anchor covers the gap by construction (a snapshot is the whole history).
Snapshots themselves are latest-wins per member, exactly like the tcp
send queue.

Correctness is unchanged from the serial path because ALL gossiped
payloads are joins: JOIN-engine deltas expand to full states whose
untouched rows are the join identity, and MONOID engines always gossip
through the versioned-row lift (row-replace is idempotent and
commutative). Apply order and duplication are therefore free —
bit-identical convergence is pinned by tests/test_overlap.py and
`make overlap-demo`.

The ingest fast path (PR 15) tightens the inbound half further: the
prefetcher fetches a RUN of range frames per peer (compacted wire
windows, `net.transport` CCRF framing), decodes them as one batch under
the `round.delta_decode` span (degrading to per-frame decode when the
`ingest.decode` fault point fires — a corrupt batch must never wedge
the chain), pre-expands BOTH topk_rmv and entrywise table deltas to
mergeable states, and pre-stages them to device asynchronously
(`core.batch_merge.stage_to_device`) so `drain_into`'s folds read
device-resident operands instead of paying h2d inside
`round.device_dispatch`.

Env knobs (all read at pipeline construction):
  CCRDT_OVERLAP        on unless set to 0/false/no/off (default ON)
  CCRDT_OVERLAP_QUEUE  apply-queue depth (default 32)
  CCRDT_OVERLAP_BATCH  max windows folded per batched dispatch (default 8)
  CCRDT_OVERLAP_HOSTQ  host-stage queue depth (default 8)
  CCRDT_INGEST_DECODE_BATCH  max inbound frames decoded per batch (default 8)
"""

from __future__ import annotations

import os
import queue
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, List, Optional

from ..obs import events as obs_events
from ..obs import spans as obs_spans

# CPU/older backends cannot alias donated buffers and warn about it per
# compile. The donation contract is honored regardless (the pipeline
# never reuses a donated operand), so the warning is noise on the CI
# backend; scoped by message, not category-wide.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

ENV_FLAG = "CCRDT_OVERLAP"
ENV_QUEUE = "CCRDT_OVERLAP_QUEUE"
ENV_BATCH = "CCRDT_OVERLAP_BATCH"
ENV_HOSTQ = "CCRDT_OVERLAP_HOSTQ"

_FALSE = ("0", "false", "no", "off")


def enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the overlap switch: an explicit CLI value wins, else
    CCRDT_OVERLAP (ON unless set to 0/false/no/off)."""
    if flag is not None:
        return bool(flag)
    return os.environ.get(ENV_FLAG, "1").strip().lower() not in _FALSE


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        return max(floor, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


def queue_depth() -> int:
    return _env_int(ENV_QUEUE, 32)


def batch_cap() -> int:
    return _env_int(ENV_BATCH, 8, floor=2)


def host_queue_depth() -> int:
    return _env_int(ENV_HOSTQ, 8)


ENV_DECODE_BATCH = "CCRDT_INGEST_DECODE_BATCH"


def decode_batch_cap() -> int:
    return _env_int(ENV_DECODE_BATCH, 8)


# -- the background host stage ------------------------------------------------


class HostStage:
    """One worker thread owning the round's host-side I/O (WAL append,
    delta encode, gossip send). A SINGLE thread on purpose: the bounded
    FIFO is the write-ahead ordering guarantee — append(step) submitted
    before publish(step) runs before it. submit() blocks when the queue
    is full (backpressure toward the round thread, billed
    `overlap.stalls`); a task exception fail-stops the stage and
    re-raises at the next submit/drain/close, so a durability failure
    cannot be silently swallowed by asynchrony. Phase spans inside tasks
    (wal_append, delta_encode, gossip_send, snapshot) land on this
    thread's tid and are therefore classified OVERLAPPABLE by
    `obs.spans.attribute`."""

    def __init__(self, metrics: Any = None, depth: int = 8, name: str = "host"):
        self.metrics = metrics
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._exc: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"overlap-{name}"
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            fn, args, kwargs = item
            try:
                if self._exc is None:  # fail-stop: drop work after a failure
                    fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — re-raised at submit
                self._exc = e
            finally:
                self._q.task_done()

    def _check(self) -> None:
        if self._exc is not None:
            exc, self._exc = self._exc, None
            self._closed = True
            raise exc

    def submit(self, fn, *args, **kwargs) -> None:
        self._check()
        if self._closed:
            raise RuntimeError("HostStage is closed")
        if self.metrics is not None and self._q.full():
            self.metrics.count("overlap.stalls")
        self._q.put((fn, args, kwargs))  # blocks when full: backpressure
        if self.metrics is not None:
            self.metrics.count("overlap.host_tasks")

    def drain(self) -> None:
        """Block until every submitted task has run (the flush barrier
        before a publish boundary the caller must observe, and before
        the final convergence loop)."""
        self._q.join()
        self._check()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._q.put(None)
            self._q.join()
            self._thread.join(timeout=10)
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc


# -- group-commit coalescer ----------------------------------------------------


class CommitCoalescer:
    """Batches WAL fsyncs across members sharing a log device (PR 11
    group commit). Every `ElasticWal` registered here stages its appends
    (group/async durability); `flush()` — called from the publish-
    boundary task running ON the HostStage, so it sits FIFO-after every
    append it covers — commits all of them: one `wal.fsync` fault fire
    and one fsync per dirty segment stream per member, instead of one
    per append. `maybe_flush()` is the time-bounded variant for call
    sites that run every round (a flush is forced anyway whenever a
    member's own byte/time backstop trips inside `log_step`).

    Single-member processes still win: consecutive ROUNDS between
    publish boundaries share one fsync (`wal.group_size` histogram
    records how many)."""

    def __init__(self, metrics: Any = None, min_interval_ms: float = 0.0):
        self.metrics = metrics
        self.min_interval_ms = float(min_interval_ms)
        self._wals: List[Any] = []
        self._last = 0.0

    def add(self, wal: Any) -> None:
        if wal is not None and wal not in self._wals:
            self._wals.append(wal)

    def flush(self) -> int:
        """Commit every registered member's staged batch. Returns the
        total records acked durable across members."""
        total = 0
        for wal in self._wals:
            total += wal.flush()
        self._last = time.monotonic()
        if total and self.metrics is not None:
            self.metrics.count("wal.coalesced_commits")
        return total

    def maybe_flush(self) -> int:
        if (time.monotonic() - self._last) * 1e3 < self.min_interval_ms:
            return 0
        return self.flush()


# -- the bounded inbound apply queue ------------------------------------------


_ALL_PARTS = -1  # hole key meaning "every partition" (legacy / unknown)


class _Entry:
    __slots__ = ("kind", "member", "seq", "payload", "merged", "parts", "lo")

    def __init__(self, kind: str, member: str, seq: int, payload: Any,
                 merged: Any, parts: Optional[frozenset] = None,
                 lo: Optional[int] = None):
        self.kind = kind          # "delta" | "snap"
        self.member = member
        self.seq = seq
        self.payload = payload    # decoded delta / fetched peer state
        self.merged = merged      # pre-expanded mergeable state, or None
        # Low edge of a range frame [lo..seq] (compacted wire windows);
        # lo == seq is the legacy single-window case. Rides into the
        # delta.apply event so the causal audit reads the jump as
        # chained coverage, not a gap-skip.
        self.lo = seq if lo is None else lo
        # Partition set this payload touches (core.partition.delta_parts
        # minus the meta partition — whole-instance leaves are shipped in
        # full by every delta and are join-monotone, so their loss heals
        # via ANY later payload). None = unknown/legacy: touches all.
        # Empty frozenset = meta-only: dropping it loses nothing durable.
        self.parts = parts


class ApplyQueue:
    """Bounded queue of pre-decoded inbound payloads, shed with the
    net/tcp.py send-queue policy: oldest DELTA first, anchors kept,
    snapshots latest-wins per member. Shedding a delta opens a HOLE
    (chained deltas are valid only gap-free) — at PARTITION granularity
    when entries carry their partition set: only the member's later
    queued deltas that INTERSECT the victim's partitions are purged with
    it, only intersecting further deltas are refused (disjoint
    partitions keep flowing), and a full snapshot with seq >= a
    partition's hole heals that partition. Entries without a partition
    set (`parts=None` — legacy callers, engines without an item plan)
    degrade to the old whole-member hole."""

    def __init__(self, depth: int = 32, metrics: Any = None):
        self.depth = max(1, depth)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._q: "deque[_Entry]" = deque()
        # member -> {partition (or _ALL_PARTS) -> min healing snap seq}
        self._holes: Dict[str, Dict[int, int]] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def dirty_floor(self, member: str) -> Optional[int]:
        """The member's widest open hole (lowest snapshot seq healing
        ALL of its holed partitions), or None when its chain is intact."""
        with self._lock:
            holes = self._holes.get(member)
            return max(holes.values()) if holes else None

    def dirty_parts(self, member: str) -> Dict[int, int]:
        """{partition -> min healing snap seq} for the member's open
        holes (`_ALL_PARTS` = every partition)."""
        with self._lock:
            return dict(self._holes.get(member, {}))

    def _count(self, name: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.count(name, n)

    @staticmethod
    def _holed(holes: Dict[int, int], parts: Optional[frozenset]) -> bool:
        """Does a payload touching `parts` hit any open hole?"""
        if not holes:
            return False
        if _ALL_PARTS in holes or parts is None:
            return True
        return any(p in holes for p in parts)

    def _shed_locked(self) -> None:
        """Make room (lock held): drop the oldest delta plus the same
        member's later queued deltas intersecting its partition set
        (partition-granular contiguity), recording a hole per touched
        partition; a meta-only delta (empty parts) drops alone and holes
        nothing — its whole-instance leaves are monotone and re-shipped
        by every later payload. A queue of only snapshots drops its
        oldest (a hole marks it for refetch — the newer anchor on the
        store still covers it)."""
        victim = next((e for e in self._q if e.kind == "delta"), None)
        if victim is not None:
            vp = victim.parts
            dropped = [victim]
            if vp is None or vp:
                dropped += [
                    e for e in self._q
                    if e.kind == "delta" and e.member == victim.member
                    and e.seq > victim.seq
                    and (vp is None or e.parts is None or (e.parts & vp))
                ]
            for e in dropped:
                self._q.remove(e)
            holes = self._holes.setdefault(victim.member, {})
            for e in dropped:
                if e.parts is None:
                    holes[_ALL_PARTS] = max(
                        holes.get(_ALL_PARTS, -1), e.seq
                    )
                else:
                    for p in e.parts:
                        holes[p] = max(holes.get(p, -1), e.seq)
            if not holes:
                self._holes.pop(victim.member, None)
            self._count("overlap.dropped_deltas", len(dropped))
            return
        e = self._q.popleft()  # all snaps: oldest snap goes
        holes = self._holes.setdefault(e.member, {})
        holes[_ALL_PARTS] = max(holes.get(_ALL_PARTS, -1), e.seq)
        self._count("overlap.dropped_snaps")

    def put_delta(self, member: str, seq: int, payload: Any,
                  merged: Any = None,
                  parts: Optional[frozenset] = None,
                  lo: Optional[int] = None) -> bool:
        """Enqueue delta `seq` of `member` (a range frame when lo < seq);
        False when refused (the delta touches a holed partition — the
        caller must stop chaining until an anchor covers it; deltas
        touching only intact partitions are still accepted)."""
        with self._lock:
            if self._holed(self._holes.get(member, {}), parts):
                return False
            if len(self._q) >= self.depth:
                self._shed_locked()
            if self._holed(self._holes.get(member, {}), parts):
                # The shed just holed (part of) THIS member's chain and
                # the incoming delta lands in the gap.
                self._count("overlap.dropped_deltas")
                return False
            self._q.append(
                _Entry("delta", member, seq, payload, merged, parts, lo=lo)
            )
            return True

    def put_snap(self, member: str, seq: int, payload: Any,
                 merged: Any = None) -> bool:
        """Enqueue a full-snapshot anchor (latest-wins per member). A
        snapshot covers every partition through `seq`, so it heals each
        hole it reaches (seq >= that partition's hole); an anchor below
        ALL open holes is refused (it cannot cover any gap). A stale
        anchor with the member's DELTAS queued behind it is kept, not
        replaced: those deltas chain from the anchor's seq, and popping
        them without it would emit a dseq jump the flight-log causal
        audit reads as a gap-skip (applying the old anchor too is just
        an extra join)."""
        with self._lock:
            holes = self._holes.get(member)
            if holes and all(seq < h for h in holes.values()):
                return False
            q = list(self._q)
            stale = [
                e for e in q if e.kind == "snap" and e.member == member
            ]
            for e in stale:
                if any(
                    e2.kind == "delta" and e2.member == member
                    for e2 in q[q.index(e) + 1:]
                ):
                    continue
                self._q.remove(e)
            if len(self._q) >= self.depth:
                self._shed_locked()
            holes = self._holes.get(member)
            if holes and all(seq < h for h in holes.values()):
                return False  # the shed re-holed us above this anchor
            self._q.append(_Entry("snap", member, seq, payload, merged))
            if holes:
                for p in [p for p, h in holes.items() if seq >= h]:
                    holes.pop(p)
                if not holes:
                    self._holes.pop(member, None)
            return True

    def pop_all(self) -> List[_Entry]:
        with self._lock:
            out = list(self._q)
            self._q.clear()
            return out


# -- the inbound prefetcher ---------------------------------------------------


class DeltaPrefetcher:
    """The fetch+validate+decode half of `elastic.sweep_deltas`, run
    AHEAD of the round: chain contiguous deltas per peer from a fetch
    cursor, fall back to the full-snapshot anchor on a gap (or an
    ApplyQueue hole), and pre-expand topk_rmv deltas to mergeable full
    states so the round thread's fold is pure device work. `poll()` is
    the thread-free core — the sim chaos test drives it synchronously
    for determinism; `start()` wraps it in a daemon thread whose
    `round.gossip_recv` spans (emitted inside the transport fetch paths)
    land on their own tid and read as OVERLAPPABLE."""

    def __init__(self, store: Any, dense: Any, like_state: Any,
                 apq: ApplyQueue, metrics: Any = None,
                 partitions: Optional[int] = None):
        from .delta import like_delta_for
        from .elastic import _resolve_monoid
        from .monoid import MonoidLift

        dense, like_state = _resolve_monoid(dense, like_state, "DeltaPrefetcher")
        self.store = store
        self.dense = dense
        self.like_state = like_state
        self.apq = apq
        self.metrics = metrics if metrics is not None else store.metrics
        # With a partition count, every decoded delta is tagged with the
        # partitions it touches (receiver-side: core.partition
        # .delta_parts) so ApplyQueue sheds/heals at partition
        # granularity. None keeps whole-member holes (legacy).
        self.partitions = partitions
        self._like_delta = like_delta_for(dense, like_state)
        # Lifted monoid states carry host-side row versions; they apply
        # through apply_monoid_row_delta / MonoidLift.merge sequentially,
        # never through the batched device fold.
        self._foldable = not isinstance(dense, MonoidLift)
        self.cursors: Dict[str, int] = {}  # highest seq ENQUEUED per member
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _fetch_snap(self, member: str, floor: int) -> int:
        """Fetch `member`'s latest anchor if it advances past `floor`;
        returns the new cursor position (or `floor` unchanged)."""
        got = self.store.fetch(member, self.like_state, dense=self.dense)
        if got is None:
            return floor
        seq, peer = got
        if seq <= floor and self.apq.dirty_floor(member) is None:
            return floor
        if self.apq.put_snap(
            member, seq, peer, peer if self._foldable else None
        ):
            self.metrics.count("overlap.prefetched_snaps")
            return max(floor, seq)
        return floor

    def _decode(self, member: str, hi: int, payload: bytes) -> Any:
        """Decode one frame payload (billed `round.delta_decode` inside
        the node). Returns the delta or None (torn/out-of-bounds)."""
        from .delta import delta_in_bounds

        return self.store.decode_delta_blob(
            member, hi, payload, self._like_delta,
            validate=lambda d: delta_in_bounds(
                self.dense, self.like_state, d
            ),
        )

    def _expand(self, delta: Any) -> Any:
        """Pre-expand a decoded delta to a mergeable full state and
        pre-stage its leaves to device (async h2d — `drain_into`'s fold
        then reads device-resident operands instead of paying the
        transfer inside `round.device_dispatch`). Best-effort: None
        keeps the sequential-apply fallback."""
        from .delta import TopkRmvDelta, expand_delta, expand_table_delta

        if not self._foldable:
            return None
        try:
            if isinstance(delta, TopkRmvDelta):
                merged = expand_delta(self.dense, delta)
            elif isinstance(delta, dict) and "idx" in delta:
                # Entrywise table deltas join the fold path too:
                # apply_table_delta IS merge(state, expand_table_delta),
                # so folding the expansion is the same join.
                merged = expand_table_delta(
                    self.dense, self.like_state, delta
                )
            else:
                return None
        except Exception:  # noqa: BLE001 — fold is best-effort
            return None
        if merged is not None:
            try:
                from ..core.batch_merge import stage_to_device, tree_nbytes

                merged = stage_to_device(merged)
                self.metrics.count(
                    "ingest.staged_bytes", tree_nbytes(merged)
                )
            except Exception:  # noqa: BLE001 — unstaged operands still
                pass  # fold; the h2d just moves back inline
        return merged

    def _parts(self, delta: Any) -> Optional[frozenset]:
        if not self.partitions:
            return None
        from ..core import partition as pt

        try:
            # Meta partition excluded: whole-instance leaves ride every
            # delta in full and are join-monotone, so they need no hole
            # bookkeeping (see _Entry).
            return frozenset(
                pt.delta_parts(
                    self.dense, self.like_state, delta, self.partitions
                )
            ) - {pt.meta_part(self.partitions)}
        except Exception:  # noqa: BLE001 — tag is best-effort
            return None  # untagged = touches-all (safe)

    def _ingest_frames(self, member: str, cur: int, frames: List) -> tuple:
        """Decode a collected run of wire frames as ONE batch, then
        expand + enqueue in chain order. The batch decode degrades to
        per-frame decode when the `ingest.decode` fault point fires (or
        the batch pass raises) — a poisoned batch must never wedge the
        prefetch chain; the per-frame total-failure policy then applies.
        Returns (new cursor, entries enqueued)."""
        if not frames:
            return cur, 0
        from ..utils import faults

        try:
            if faults.ACTIVE and faults.fire("ingest.decode") != "ok":
                raise RuntimeError("ingest.decode: degraded batch")
            decoded = [
                self._decode(member, hi, payload)
                for _lo, hi, payload in frames
            ]
        except Exception:  # noqa: BLE001 — degrade, never wedge
            self.metrics.count("ingest.decode_degraded")
            decoded = []
            for _lo, hi, payload in frames:
                try:
                    decoded.append(self._decode(member, hi, payload))
                except Exception:  # noqa: BLE001
                    decoded.append(None)
        n = 0
        for (lo, hi, _payload), delta in zip(frames, decoded):
            if delta is None:
                break  # torn/mismatched write: retry next poll
            merged = self._expand(delta)
            parts = self._parts(delta)
            if not self.apq.put_delta(
                member, hi, delta, merged, parts, lo=lo
            ):
                break  # queue holed this member: anchor path next poll
            cur = hi
            n += 1
            self.metrics.count("overlap.prefetched_deltas")
        return cur, n

    def poll(self) -> int:
        """One prefetch pass over every peer; returns entries enqueued."""
        store = self.store
        cap = decode_batch_cap()
        n = 0
        members = sorted(
            set(store.snapshot_members()) | set(store.delta_members())
        )
        for m in members:
            if m == store.member:
                continue
            cur = self.cursors.get(m, -1)
            hole = self.apq.dirty_floor(m)
            if hole is not None and self.partitions is None:
                # Anchor-only until the hole is covered: deltas past a
                # dropped seq can never restore chain contiguity. (With
                # partitions, holes are per-partition — keep chaining and
                # let put_delta refuse only intersecting deltas; the
                # trailing anchor fetch below covers the holed ones.)
                snap_seq = store.snapshot_seq(m)
                if snap_seq is not None and snap_seq >= hole:
                    new = self._fetch_snap(m, cur)
                    n += int(new > cur)
                    cur = new
                self.cursors[m] = cur
                continue
            avail = sorted(store.delta_seqs(m))
            # Frames live under their HIGH seq; [lo..hi] chains from the
            # cursor iff lo <= cur+1 (the legacy frame is lo == hi).
            nxt = next((s for s in avail if s > cur), None)
            head = store.fetch_delta_blob(m, nxt) if nxt is not None else None
            if head is None or head[0] > cur + 1:
                # First contact (or a pruned/compacted tail): the chain
                # cannot start from here, so land the anchor FIRST — one
                # poll then yields anchor + the frames chained behind it,
                # instead of burning a second pass. When the chain IS
                # walkable the anchor is skipped: deltas are cheaper.
                snap_seq = store.snapshot_seq(m)
                if snap_seq is not None and snap_seq > cur:
                    new = self._fetch_snap(m, cur)
                    n += int(new > cur)
                    cur = new
            while True:
                # Collect the walkable frame run (wire fetches billed
                # `round.gossip_recv` inside fetch_delta_blob), then
                # batch-decode + enqueue it.
                frames = []
                walk = cur
                while len(frames) < cap:
                    if head is not None:
                        fr, head = head, None
                        if fr[1] <= walk:
                            continue  # anchor already covered the head
                    else:
                        nx = next((s for s in avail if s > walk), None)
                        if nx is None:
                            break
                        fr = store.fetch_delta_blob(m, nx)
                    if fr is None or fr[0] > walk + 1:
                        break
                    frames.append(fr)
                    walk = fr[1]
                if not frames:
                    break
                cur2, got = self._ingest_frames(m, cur, frames)
                n += got
                stalled = cur2 < frames[-1][1]
                cur = max(cur, cur2)
                if stalled:
                    break  # torn frame or holed queue: resume next poll
            snap_seq = store.snapshot_seq(m)
            if snap_seq is not None and snap_seq > cur:
                new = self._fetch_snap(m, cur)
                n += int(new > cur)
                cur = new
            self.cursors[m] = cur
        return n

    def start(self, interval: float = 0.002) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(interval,), daemon=True,
            name="overlap-prefetch",
        )
        self._thread.start()

    def _run(self, interval: float) -> None:
        while not self._stop.is_set():
            try:
                n = self.poll()
            except Exception:  # noqa: BLE001 — a flaky peer must not
                # kill prefetching for the rest; transports are already
                # total, so this counts real bugs loudly in metrics.
                self.metrics.count("overlap.prefetch_errors")
                n = 0
            if not n:
                self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


# -- the pipeline facade ------------------------------------------------------


class OverlapPipeline:
    """What `run_worker` holds in overlap mode: the host stage (outbound
    WAL/encode/send), the prefetcher+apply queue (inbound), the
    fold-and-apply drain, and the APPLIED per-peer watermarks
    (`cursors`) that the lag tracker and status drops read."""

    def __init__(self, store: Any, dense: Any, like_state: Any, *,
                 metrics: Any = None, depth: Optional[int] = None,
                 fold_cap: Optional[int] = None,
                 host_depth: Optional[int] = None,
                 start_thread: bool = True,
                 partitions: Optional[int] = None,
                 post_fold: Optional[Any] = None,
                 pager: Optional[Any] = None):
        self.metrics = metrics if metrics is not None else store.metrics
        # Out-of-core residency (core/pager.py). While any partition is
        # cold, inbound payloads must route through the pager (hot half
        # on device, cold half folded host-side) — merging a full
        # expanded window straight into the device state would land rows
        # the pager's cached cold digests can't see.
        self.pager = pager
        # Mesh hook (mesh/reduce.py): called as post_fold(state) on the
        # ROUND thread after a drain actually folded windows in —
        # exactly where the intra-slice ICI reduce belongs (fresh peer
        # rows just landed; pre-join them before the next publish).
        # Must be total and must NOT donate its operand: the host stage
        # may still be serializing buffers of the state it receives.
        self.post_fold = post_fold
        self.apq = ApplyQueue(
            depth if depth is not None else queue_depth(),
            metrics=self.metrics,
        )
        self.prefetch = DeltaPrefetcher(
            store, dense, like_state, self.apq, metrics=self.metrics,
            partitions=partitions,
        )
        self.dense = self.prefetch.dense
        self.host = HostStage(
            metrics=self.metrics,
            depth=host_depth if host_depth is not None else host_queue_depth(),
        )
        self.fold_cap = fold_cap if fold_cap is not None else batch_cap()
        self.cursors: Dict[str, int] = {}  # highest seq APPLIED per member
        if start_thread:
            self.prefetch.start()

    def submit(self, fn, *args, **kwargs) -> None:
        self.host.submit(fn, *args, **kwargs)

    def pressure_depth(self) -> int:
        """Combined backlog an admission controller should gate on: the
        inbound apply queue PLUS the outbound host-stage queue (pending
        WAL appends / encodes / sends). The write tier's ingest plane
        (PR 16) sheds writers on this — a deep host queue means acks
        would stack behind fsync work the pipeline hasn't run yet."""
        return len(self.apq) + self.host._q.qsize()

    def _apply_sequential(self, state: Any, entries: List[_Entry]) -> Any:
        """Fallback / non-foldable application, entry by entry with the
        sweep_deltas total-failure policy (a malformed payload must not
        crash the round)."""
        from .delta import apply_any_delta

        pager = self.pager
        for e in entries:
            try:
                if pager is not None and pager.has_cold():
                    if e.kind == "snap":
                        state = self.dense.merge(
                            state, pager.absorb_peer(e.payload)
                        )
                    else:
                        state = pager.apply_delta(state, e.payload)
                elif e.kind == "snap":
                    state = self.dense.merge(state, e.payload)
                else:
                    state = apply_any_delta(self.dense, state, e.payload)
            except Exception:  # noqa: BLE001 — deliberately total
                self.metrics.count("overlap.apply_errors")
        return state

    def drain_into(self, state: Any) -> Any:
        """Fold every queued window into `state` on the ROUND thread:
        mergeable entries (pre-expanded deltas + JOIN snapshots) go
        through `core.batch_merge.fold_states` in chunks of `fold_cap`
        — >=2 windows become ONE batched dispatch chain — the rest apply
        sequentially. Join algebra makes the order irrelevant; the
        flight-recorder apply events are emitted in queue order, which
        preserves per-member seq contiguity for `ccrdt_trace audit`."""
        # The span brackets the WHOLE apply stage — queue pop, fold
        # dispatch, the sequential fallback, AND the apply-event
        # bookkeeping. Any of these can absorb tens of ms (the pop and
        # the dispatch both ride behind the previous round's chained
        # device work), so billing only the inner merge section left
        # that wall time as unattributed gap in `spans.attribute`. The
        # span's m0 is backdated over the pop (an empty drain emits no
        # span at all — near-zero samples would skew the phase p50s).
        t0 = time.monotonic() if obs_spans.ACTIVE else None
        entries = self.apq.pop_all()
        if not entries:
            return state
        from ..core.batch_merge import fold_states, merge_into

        if self.pager is not None and self.pager.has_cold():
            # Mixed residency: the batched fold would write cold
            # partitions' rows onto the device behind the pager's back.
            # Everything goes through the pager-aware sequential path.
            mergeable, rest = [], entries
        else:
            mergeable = [e for e in entries if e.merged is not None]
            rest = [e for e in entries if e.merged is None]
        tok = (
            obs_spans.begin(
                "round.delta_apply", via="overlap", n=len(entries)
            )
            if obs_spans.ACTIVE
            else None
        )
        if tok is not None:
            tok["m0"] = t0
        try:
            merge = self.dense.merge
            i = 0
            while i < len(mergeable):
                chunk = mergeable[i:i + self.fold_cap]
                i += len(chunk)
                try:
                    if len(chunk) >= 2:
                        state = fold_states(
                            merge, [state] + [e.merged for e in chunk]
                        )
                        self.metrics.count("overlap.folds")
                        self.metrics.count(
                            "overlap.folded_windows", len(chunk)
                        )
                        # Cross-member fused apply: the chunk rides the
                        # queue in arrival order, so windows from
                        # DIFFERENT peers stack into the same batched
                        # dispatch (the join is law-certified
                        # commutative/associative — member boundaries
                        # mean nothing to it).
                        self.metrics.count(
                            "ingest.fused_members",
                            len({e.member for e in chunk}),
                        )
                    else:
                        state = merge_into(merge, state, chunk[0].merged)
                except Exception:  # noqa: BLE001 — fall back per entry
                    state = self._apply_sequential(state, chunk)
            state = self._apply_sequential(state, rest)
            for e in entries:
                if e.kind == "delta":
                    obs_events.emit(
                        "delta.apply", origin=e.member, dseq=e.seq,
                        lo=e.lo,
                    )
                else:
                    obs_events.emit(
                        "snap.apply", origin=e.member, step=e.seq
                    )
                if e.seq > self.cursors.get(e.member, -1):
                    self.cursors[e.member] = e.seq
        finally:
            obs_spans.end(tok)
        self.metrics.count("overlap.windows", len(entries))
        if self.post_fold is not None:
            state = self.post_fold(state)
        return state

    def close(self, state: Any) -> Any:
        """Flush at end of the step loop: host tasks durable (WAL tail +
        last publishes), prefetcher stopped, queue remnants folded in.
        The caller then runs the ordinary SERIAL final-convergence loop
        — it must keep adopting late-detected deaths and needs no
        pipeline."""
        self.host.drain()
        self.prefetch.stop()
        state = self.drain_into(state)
        self.host.close()
        return state
