"""Orbax-backed gossip for mesh-sharded states: the geo-DR tier.

`parallel.elastic.GossipStore` ships host-local npz snapshots — right for
states that fit one host. A SITE in the multihost layout
(`parallel.multihost`) holds its state *sharded over a device mesh*; its
snapshots must be written shard-parallel (each host writes what it owns)
and restored onto a DIFFERENT site's mesh shape. That is exactly what
Orbax does (`harness.orbax_ckpt`), so this module is the thin composition:

* publish  = Orbax save of the sharded state under `<root>/<member>/` +
  the same mtime heartbeat files `GossipStore` uses (one failure
  detector across both tiers).
* fetch    = Orbax restore of a PEER's latest step into THIS site's
  shardings (cross-mesh resharding is Orbax's native move).
* sweep    = fold every peer's latest snapshot in with the engine join —
  identical semantics to the host-local tier: stale snapshots, repeated
  merges, and membership churn are all absorbed by join idempotence.

Cross-site anti-entropy over shared storage is the CRDT-native
disaster-recovery plane: no cross-site collectives, no coordinator, and a
site restored from the store is immediately mergeable.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from ..harness import orbax_ckpt
from .elastic import GossipStore


def available() -> bool:
    return orbax_ckpt.available()


class OrbaxGossip:
    """Per-member Orbax checkpoint trees + shared heartbeat files.

    Layout: `<root>/hb-<member>` (heartbeats, via GossipStore) and
    `<root>/ckpt-<member>/<step>/` (Orbax-managed, retention-pruned)."""

    def __init__(self, root: str, member: str, max_to_keep: int = 2):
        self.root = root
        self.member = member
        self._hb = GossipStore(root, member)  # heartbeat + liveness surface
        self._mgr = orbax_ckpt.DenseCheckpointManager(
            os.path.join(os.path.abspath(root), f"ckpt-{member}"),
            max_to_keep=max_to_keep,
        )
        self._peer_mgrs: Dict[str, Any] = {}

    # liveness delegates to the shared heartbeat files
    def heartbeat(self) -> None:
        self._hb.heartbeat()

    def members(self) -> List[str]:
        return self._hb.members()

    def alive_members(self, timeout_s: float) -> List[str]:
        return self._hb.alive_members(timeout_s)

    # -- snapshots ---------------------------------------------------------

    def publish(self, state: Any, step: int) -> None:
        """Shard-parallel save of this site's (possibly mesh-sharded)
        state; every host of the site calls this collectively."""
        self._mgr.save(step, state)
        self._hb.heartbeat()

    def _peer_mgr(self, member: str) -> Optional[Any]:
        d = os.path.join(os.path.abspath(self.root), f"ckpt-{member}")
        if not os.path.isdir(d):
            return None
        if member not in self._peer_mgrs:
            self._peer_mgrs[member] = orbax_ckpt.DenseCheckpointManager(
                d, max_to_keep=10**6  # reader: never prune a peer's steps
            )
        return self._peer_mgrs[member]

    def snapshot_members(self) -> List[str]:
        return sorted(
            d[len("ckpt-"):]
            for d in os.listdir(self.root)
            if d.startswith("ckpt-")
            and os.path.isdir(os.path.join(self.root, d))
        )

    def peer_latest_step(self, member: str) -> Optional[int]:
        """Peer's newest published step. Orbax managers cache their step
        list at construction and only refresh it on their OWN saves, so a
        reader MUST `reload()` before looking — without it, a cached peer
        manager pins the step it saw first, the owner's retention soon
        prunes that step, and every later fetch turns into a silent None:
        gossip stops converging after the first exchange (verified against
        orbax 0.11.32)."""
        try:
            mgr = self._peer_mgr(member)
            if mgr is None:
                return None
            mgr.reload()
            return mgr.latest_step()
        except Exception:  # noqa: BLE001 — deliberately total
            return None

    def fetch(self, member: str, like: Any) -> Optional[Tuple[int, Any]]:
        """Peer's latest snapshot restored INTO `like`'s shardings (this
        site's mesh) — or None on any failure, same total-failure policy
        as the host-local tier (the next sweep retries)."""
        step = self.peer_latest_step(member)
        if step is None:
            return None
        try:
            return step, self._peer_mgr(member).restore(like, step=step)
        except Exception:  # noqa: BLE001 — deliberately total
            return None

    def sweep(
        self, dense: Any, state: Any, cursors: Optional[Dict[str, int]] = None
    ) -> Tuple[Any, int]:
        """Join every peer's latest snapshot into `state`. `cursors`
        (member -> last merged step, updated in place) skips peers whose
        publish has not advanced — a full cross-mesh restore of a large
        sharded state is the dominant cost of a sweep and is pure waste
        when the data is already reflected."""
        from .elastic import _resolve_monoid

        dense, state = _resolve_monoid(dense, state, "OrbaxGossip.sweep")
        n = 0
        for m in self.snapshot_members():
            if m == self.member:
                continue
            if cursors is not None:
                latest = self.peer_latest_step(m)
                if latest is None or latest <= cursors.get(m, -1):
                    continue
            got = self.fetch(m, state)
            if got is None:
                continue
            step, peer = got
            state = dense.merge(state, peer)
            n += 1
            if cursors is not None:
                cursors[m] = step
        return state, n

    def close(self) -> None:
        self._mgr.close()
        for mgr in self._peer_mgrs.values():
            mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
