"""Shared helpers for direct-indexed dense score tables.

Used by the dense topk and leaderboard kernels (and NEG_INF by topk_rmv):
a per-id best-score table [R, NK, P] whose observable is the masked top-K,
derived by one 2-key sort — score desc, id desc tiebreak, matching both
reference cmp orders (topk.erl:83, leaderboard.erl:289-294).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Safe "minus infinity" score sentinel: negatable in int32.
NEG_INF = jnp.int32(-(2**31 - 1))


def masked_topk(scores: jax.Array, k: int):
    """(ids, scores, valid) of the top-k entries of a [..., P] score table;
    NEG_INF marks absent entries."""
    ids = jnp.broadcast_to(
        jnp.arange(scores.shape[-1], dtype=jnp.int32), scores.shape
    )
    ns, ni = lax.sort((-scores, -ids), num_keys=2, dimension=-1)
    top = -ns[..., :k]
    return (-ni[..., :k], top, top > NEG_INF)


def observe_value(observe_fn, state):
    """Materialize an (ids, scores, valid) observable to host as nested
    [(id, score)] lists per (replica, instance) — the value/1 shape."""
    ids, scores, valid = jax.device_get(observe_fn(state))
    R, NK, K = ids.shape
    return [
        [
            [
                (int(ids[r, nk, j]), int(scores[r, nk, j]))
                for j in range(K)
                if valid[r, nk, j]
            ]
            for nk in range(NK)
        ]
        for r in range(R)
    ]


def observables_equal(a_obs, b_obs) -> bool:
    """Observable-state equality on (ids, scores, valid) triples."""
    ia, sa, va = a_obs
    ib, sb, vb = b_obs
    return bool(
        jnp.all(
            (va == vb)
            & jnp.where(va, ia == ib, True)
            & jnp.where(va, sa == sb, True)
        )
    )
