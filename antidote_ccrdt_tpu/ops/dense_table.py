"""Shared helpers for direct-indexed dense score tables.

Used by the dense topk and leaderboard kernels (and NEG_INF by topk_rmv):
a per-id best-score table [R, NK, P] whose observable is the masked top-K,
derived by one 2-key sort — score desc, id desc tiebreak, matching both
reference cmp orders (topk.erl:83, leaderboard.erl:289-294).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Safe "minus infinity" score sentinel: negatable in int32.
NEG_INF = jnp.int32(-(2**31 - 1))


def masked_topk(scores: jax.Array, k: int):
    """(ids, scores, valid) of the top-k entries of a [..., P] score table;
    NEG_INF marks absent entries."""
    ids = jnp.broadcast_to(
        jnp.arange(scores.shape[-1], dtype=jnp.int32), scores.shape
    )
    ns, ni = lax.sort((-scores, -ids), num_keys=2, dimension=-1)
    top = -ns[..., :k]
    return (-ni[..., :k], top, top > NEG_INF)


def observe_value(observe_fn, state):
    """Materialize an (ids, scores, valid) observable to host as nested
    [(id, score)] lists per (replica, instance) — the value/1 shape."""
    ids, scores, valid = jax.device_get(observe_fn(state))
    R, NK, K = ids.shape
    return [
        [
            [
                (int(ids[r, nk, j]), int(scores[r, nk, j]))
                for j in range(K)
                if valid[r, nk, j]
            ]
            for nk in range(NK)
        ]
        for r in range(R)
    ]


def promotion_mask(
    new_cols,
    new_valid: jax.Array,
    old_cols,
    old_valid: jax.Array,
    batch_key: jax.Array,
    batch_cols,
    batch_valid: jax.Array,
) -> jax.Array:
    """Which entries of a new observable were *uncovered* (promoted) rather
    than carried over or freshly added — the shared core of extra-op
    collection for topk_rmv (:291-295) and leaderboard (:279-283).

    Identity is the tuple of column arrays: `new_cols`/`old_cols` are
    [R, NK, K] observables, `batch_cols` are [R, B] add columns matched only
    against adds targeting the same instance (`batch_key == nk`). Returns
    the promoted mask [R, NK, K]: valid entries present in neither."""

    def all_eq(pairs):
        acc = None
        for n, o in pairs:
            eq = n == o
            acc = eq if acc is None else (acc & eq)
        return acc

    in_old = jnp.any(
        all_eq((n[..., :, None], o[..., None, :]) for n, o in zip(new_cols, old_cols))
        & old_valid[..., None, :],
        axis=-1,
    )
    NK = new_valid.shape[1]
    nk = jnp.arange(NK, dtype=jnp.int32)[None, :, None, None]
    in_batch = jnp.any(
        all_eq(
            (n[..., :, None], b[:, None, None, :])
            for n, b in zip(new_cols, batch_cols)
        )
        & (batch_key[:, None, None, :] == nk)
        & batch_valid[:, None, None, :],
        axis=-1,
    )
    return new_valid & ~in_old & ~in_batch


def observables_equal(a_obs, b_obs) -> bool:
    """Observable-state equality on (ids, scores, valid) triples."""
    ia, sa, va = a_obs
    ib, sb, vb = b_obs
    return bool(
        jnp.all(
            (va == vb)
            & jnp.where(va, ia == ib, True)
            & jnp.where(va, sa == sb, True)
        )
    )
