"""Shared helpers for direct-indexed dense score tables.

Used by the dense topk and leaderboard kernels (and NEG_INF by topk_rmv):
a per-id best-score table [R, NK, P] whose observable is the masked top-K,
derived by one 2-key sort — score desc, id desc tiebreak, matching both
reference cmp orders (topk.erl:83, leaderboard.erl:289-294).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Safe "minus infinity" score sentinel: negatable in int32. A numpy scalar
# (not jnp) so importing the package never initializes a JAX backend —
# multi-process setups must be able to import, then configure jax.distributed
# (parallel/multihost.py) before the first device op.
NEG_INF = np.int32(-(2**31 - 1))


def dedup_rows_run_max(rows: jax.Array, upd: jax.Array, n_rows: int):
    """Collapse duplicate scatter rows to run heads carrying the run max.

    Sort updates by row; a reverse segmented max gives every element its
    run's per-column total; only each run's first element keeps its row
    index (the rest point at the `n_rows` sentinel, which no consumer
    matches). Shared prepass of `scatter_max_rows_mxu` and the pallas
    one-hot tombstone kernel — both need each table row to receive at most
    one update so a sum-of-products accumulation equals that update.

    rows [Br] i32, upd [Br, D] i32. Returns (head_rows [Br], total [Br, D]).

    The suffix run-max is `segment.run_max`'s log-step doubling loop
    rather than `lax.associative_scan` with a (key, val) combiner: the
    scan's odd/even tree lowers to ~log(Br) levels of strided slice/pad
    ops that XLA schedules as separate fusions (~1.4ms visible in the
    round-4 device profile plus tail), while the doubling loop is shift +
    where chains that fuse flat. Measured in full-apply composition at
    north-star shapes (benchmarks/residual_probe.py probe M):
    ~54.7 -> ~49.2ms. The sorted row ids serve directly as run_max's
    segment ids (equality-compared only; values >= 0 never match its -1
    shift fill).
    """
    from .segment import run_max

    order = jnp.argsort(rows)
    r_s = jnp.take_along_axis(rows, order, axis=0)
    u_s = jnp.take_along_axis(upd, order[:, None], axis=0)
    total = run_max(u_s, r_s, direction="suffix")
    is_head = jnp.concatenate([jnp.ones((1,), bool), r_s[1:] != r_s[:-1]])
    head_rows = jnp.where(is_head, r_s, n_rows)
    return head_rows, total


def scatter_max_rows_mxu(
    table: jax.Array, rows: jax.Array, upd: jax.Array
) -> jax.Array:
    """``table.at[rows].max(upd)`` for non-negative i32 updates, computed on
    the MXU instead of XLA's scatter.

    XLA lowers scatter to a serialized per-row read-modify-write loop —
    measured ~29ms for 256 rows x 32 lanes into [100k, 32] on v5e (honest
    device timing; `block_until_ready` does not block on tunneled devices,
    so earlier sub-ms figures were dispatch-only). The one-hot matmul:

    1. sort updates by row; per-column suffix-max gives each duplicate run's
       head the run total (vc entries merge by per-DC max);
    2. non-head duplicates are pointed at an out-of-range row, so each table
       row receives at most ONE update and the matmul's sum == that value;
    3. exactness: the one-hot is int8 and the values split into five 7-bit
       planes packed side by side along the output axis, so the whole
       update is ONE s8 x s8 -> s32 matmul (native MXU int path). Every
       product is 0/1 x [0,128) and each output cell receives at most one
       nonzero term (step 2), so s32 accumulation is exact.

    Measured v5e, Br=1024, [100k, 32] table, 32 replicas under vmap:
    XLA scatter ~31ms; f32 hi/lo matmul pair via Precision.HIGHEST (the
    previous scheme — compiles to the slow 6-pass f32 path) ~21-27ms;
    this s8 plane packing ~19ms.

    table [T, D] i32 >= 0, rows [Br] i32 (values >= T are dropped),
    upd [Br, D] i32 >= 0. Returns the updated [T, D] table.
    """
    T, D = table.shape
    head_rows, total = dedup_rows_run_max(rows, upd, T)

    onehot = (
        head_rows[:, None] == jnp.arange(T, dtype=jnp.int32)[None, :]
    ).astype(jnp.int8)  # [Br, T]
    # 5 x 7-bit planes cover the 31 value bits. (A 4 x 8-bit packing with
    # `& 0xFF` recovery was tried to shrink the [T, n_planes*D] output 20%
    # — it regressed the apply round 40ms -> 116ms on v5e; the sign-wrapped
    # planes/masked consumers evidently knock the dot off its fast path.
    # Keep planes unsigned-in-s8.)
    n_planes = 5
    planes = jnp.concatenate(
        [((total >> (7 * k)) & 0x7F).astype(jnp.int8) for k in range(n_planes)],
        axis=-1,
    )  # [Br, n_planes * D]
    out = lax.dot_general(
        onehot, planes, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [T, n_planes * D]
    delta = jnp.zeros((T, D), jnp.int32)
    for k in range(n_planes):
        delta = delta | (out[:, k * D : (k + 1) * D] << (7 * k))
    return jnp.maximum(table, delta)


_CHUNK = 2048  # hierarchical-selection chunk width


def masked_topk(scores: jax.Array, k: int):
    """(ids, scores, valid) of the top-k entries of a [..., P] score table;
    NEG_INF marks absent entries. Order: score desc, id desc (both
    reference cmp tiebreaks, topk.erl:83 / leaderboard.erl:289-294).

    Exact hierarchical selection (cf. TopkRmvDense.observe): the global
    top-k of a total order is contained in the union of per-chunk top-ks,
    so each level replaces one huge 2-operand sort with chunked sorts and
    a candidate re-sort, recursing while the candidate set is still wide
    (a 1M-player leaderboard runs two levels: 1M -> ~49k -> ~2.4k).
    Chunk padding carries id -1 and sorts after every real entry; it can
    only surface once real entries are exhausted, where valid is False.
    """
    neg_s = -scores
    neg_i = jnp.broadcast_to(
        -jnp.arange(scores.shape[-1], dtype=jnp.int32), scores.shape
    )
    # A level shrinks the candidate set to ceil(P/_CHUNK)*k, so it provably
    # halves only for k <= _CHUNK//2 — beyond that a level can stall (or
    # even grow) and the loop would hang at trace time; fall through to the
    # single full sort in that regime.
    while k <= _CHUNK // 2 and neg_s.shape[-1] > 2 * _CHUNK:
        P = neg_s.shape[-1]
        PP = ((P + _CHUNK - 1) // _CHUNK) * _CHUNK
        pad = [(0, 0)] * (neg_s.ndim - 1) + [(0, PP - P)]
        # Padding must sort last: -NEG_INF is the largest ascending key;
        # id -1 gives -id = 1 > any real -id at equal score.
        neg_s = jnp.pad(neg_s, pad, constant_values=-NEG_INF)
        neg_i = jnp.pad(neg_i, pad, constant_values=1)
        G = PP // _CHUNK
        kk = min(k, _CHUNK)
        chunked = (*neg_s.shape[:-1], G, _CHUNK)
        ns, ni = lax.sort(
            (neg_s.reshape(chunked), neg_i.reshape(chunked)),
            num_keys=2, dimension=-1,
        )
        flat = (*neg_s.shape[:-1], G * kk)
        neg_s = ns[..., :kk].reshape(flat)
        neg_i = ni[..., :kk].reshape(flat)
    ns, ni = lax.sort((neg_s, neg_i), num_keys=2, dimension=-1)
    kf = min(k, ns.shape[-1])
    top = -ns[..., :kf]
    ids = -ni[..., :kf]
    return ids, top, (top > NEG_INF) & (ids >= 0)


def observe_value(observe_fn, state):
    """Materialize an (ids, scores, valid) observable to host as nested
    [(id, score)] lists per (replica, instance) — the value/1 shape."""
    ids, scores, valid = jax.device_get(observe_fn(state))
    R, NK, K = ids.shape
    return [
        [
            [
                (int(ids[r, nk, j]), int(scores[r, nk, j]))
                for j in range(K)
                if valid[r, nk, j]
            ]
            for nk in range(NK)
        ]
        for r in range(R)
    ]


def promotion_mask(
    new_cols,
    new_valid: jax.Array,
    old_cols,
    old_valid: jax.Array,
    batch_key: jax.Array,
    batch_cols,
    batch_valid: jax.Array,
) -> jax.Array:
    """Which entries of a new observable were *uncovered* (promoted) rather
    than carried over or freshly added — the shared core of extra-op
    collection for topk_rmv (:291-295) and leaderboard (:279-283).

    Identity is the tuple of column arrays: `new_cols`/`old_cols` are
    [R, NK, K] observables, `batch_cols` are [R, B] add columns matched only
    against adds targeting the same instance (`batch_key == nk`). Returns
    the promoted mask [R, NK, K]: valid entries present in neither."""

    def all_eq(pairs):
        acc = None
        for n, o in pairs:
            eq = n == o
            acc = eq if acc is None else (acc & eq)
        return acc

    in_old = jnp.any(
        all_eq((n[..., :, None], o[..., None, :]) for n, o in zip(new_cols, old_cols))
        & old_valid[..., None, :],
        axis=-1,
    )
    NK = new_valid.shape[1]
    nk = jnp.arange(NK, dtype=jnp.int32)[None, :, None, None]
    in_batch = jnp.any(
        all_eq(
            (n[..., :, None], b[:, None, None, :])
            for n, b in zip(new_cols, batch_cols)
        )
        & (batch_key[:, None, None, :] == nk)
        & batch_valid[:, None, None, :],
        axis=-1,
    )
    return new_valid & ~in_old & ~in_batch


def observables_equal(a_obs, b_obs) -> bool:
    """Observable-state equality on (ids, scores, valid) triples."""
    ia, sa, va = a_obs
    ib, sb, vb = b_obs
    return bool(
        jnp.all(
            (va == vb)
            & jnp.where(va, ia == ib, True)
            & jnp.where(va, sa == sb, True)
        )
    )
