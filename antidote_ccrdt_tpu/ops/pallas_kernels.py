"""Pallas TPU kernels for the topk_rmv hot paths.

SURVEY.md §7 step 6 reserves pallas for ops where XLA falls short. The two
candidates below were built and differentially verified. Honest v5e device
timings (host-readback-synced, scan-amortized dispatch — see
benchmarks/profile_topk_rmv_pieces.py for why `block_until_ready`-based
numbers on this backend are phantoms) decided what the dense model
actually dispatches to:

* **Slot sorting** (`sort_slots_pallas`) — the join step of
  `apply_ops`/`merge` sorts W<=8-wide slot groups best-first per
  (replica, key, id) row: a fixed-size compare-exchange network (Batcher
  odd-even mergesort) where each comparator is a handful of VPU selects.
  Honest timing at [32, 1, 100k, 8]: ~42ms vs ~11ms for XLA's variadic
  `lax.sort` — narrow-array sublane<->lane relayouts dominate, so **XLA
  remains the default**; the kernel is kept as verified infrastructure
  (it wins when data already lives in a [W, N] layout). It also fails the
  tunnel's remote compile when nested inside `lax.scan` (HTTP 500).

* **Tombstone row scatter-max** (`scatter_max_rows_pallas`) — the
  BlockSpec-pipelined version is rejected by Mosaic (last-two-dims tiling
  rule vs narrow D=32 minor dim) and a manual-DMA variant deadlocked on
  v5e, so the TPU path is **not wired into the hot path**; the kernel is
  interpret-verified, and the design note that matters survives in
  `combine_duplicate_rows`: rewriting every duplicate row to carry its
  run's total makes all writes idempotent-to-final, defusing
  read-modify-write races in any pipelined scatter. The production
  replacement for XLA's serialized scatter (honest cost ~29ms for 256
  rows x 32 lanes into [100k, 32]) is the dedup + one-hot MXU matmul in
  `ops.dense_table.scatter_max_rows_mxu` (~6.5ms), which also sidesteps
  the race entirely.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import dense_table


def _side_effect_params():
    """`pltpu.CompilerParams(has_side_effects=True)` where pallas has it
    (JAX >= 0.6); 0.4.x pallas has no side-effect channel at all, and the
    DMA kernel's correctness rides on the input/output alias either way —
    the flag only guards the store against DCE when outputs go unused."""
    if hasattr(pltpu, "CompilerParams"):
        return pltpu.CompilerParams(has_side_effects=True)
    return None

# Python int (not a jnp scalar): pallas kernels may not capture traced
# constants, and pad values must be static anyway. int() keeps the value
# coupled to the XLA reference path's sentinel.
NEG_INF = int(dense_table.NEG_INF)


# --- comparator network ---------------------------------------------------


def oddeven_network(n: int) -> List[Tuple[int, int]]:
    """Batcher odd-even mergesort comparator pairs for `n` inputs.

    Generated for the next power of two; pairs touching virtual inputs
    >= n are dropped, which is sound because missing inputs rank strictly
    last (empty slots hold (NEG_INF, ts=0)) and a descending
    compare-exchange never moves a minimal element up."""
    m = 1
    while m < n:
        m *= 2
    pairs: List[Tuple[int, int]] = []

    def merge(lo: int, cnt: int, r: int) -> None:
        step = r * 2
        if step < cnt:
            merge(lo, cnt, step)
            merge(lo + r, cnt, step)
            for i in range(lo + r, lo + cnt - r, step):
                pairs.append((i, i + r))
        else:
            pairs.append((lo, lo + r))

    def sort(lo: int, cnt: int) -> None:
        if cnt > 1:
            half = cnt // 2
            sort(lo, half)
            sort(lo + half, half)
            merge(lo, cnt, 1)

    sort(0, m)
    return [(i, j) for (i, j) in pairs if j < n]


def _cmpx_desc(rows, i: int, j: int):
    """Compare-exchange rows i,j of (score, ts, dc) row-lists so that row i
    ranks >= row j in (score desc, ts desc, dc asc) order — the `_sort_slots`
    key order."""
    s, t, d = rows
    si, sj = s[i], s[j]
    ti, tj = t[i], t[j]
    di, dj = d[i], d[j]
    swap = (sj > si) | ((sj == si) & ((tj > ti) | ((tj == ti) & (dj < di))))
    s[i], s[j] = jnp.where(swap, sj, si), jnp.where(swap, si, sj)
    t[i], t[j] = jnp.where(swap, tj, ti), jnp.where(swap, ti, tj)
    d[i], d[j] = jnp.where(swap, dj, di), jnp.where(swap, di, dj)


def _sort_slots_kernel(W: int, s_ref, d_ref, t_ref, os_ref, od_ref, ot_ref, nl_ref):
    # Blocks arrive [tile, W]; transpose in VMEM so the W slots live on the
    # sublane axis and every comparator is a full-width VPU select. This
    # keeps HBM traffic at exactly read-input + write-output (an XLA-level
    # pre-transpose would double it).
    s_t = s_ref[:].T
    d_t = d_ref[:].T
    t_t = t_ref[:].T
    s = [s_t[i, :] for i in range(W)]
    t = [t_t[i, :] for i in range(W)]
    d = [d_t[i, :] for i in range(W)]
    net = oddeven_network(W)
    for (i, j) in net:
        _cmpx_desc((s, t, d), i, j)
    # Adjacent dedup: in a sorted run of identical (score, ts, dc) triples
    # every element but the first matches its predecessor. Empty (ts=0)
    # slots are never deduped.
    empty_s = jnp.full_like(s[0], NEG_INF)
    zero = jnp.zeros_like(t[0])
    for i in range(W - 1, 0, -1):
        dup = (s[i] == s[i - 1]) & (t[i] == t[i - 1]) & (d[i] == d[i - 1]) & (t[i] > 0)
        s[i] = jnp.where(dup, empty_s, s[i])
        t[i] = jnp.where(dup, zero, t[i])
        d[i] = jnp.where(dup, zero, d[i])
    # Second pass pushes the holes to the end.
    for (i, j) in net:
        _cmpx_desc((s, t, d), i, j)
    n_live = zero
    for i in range(W):
        n_live = n_live + (t[i] > 0).astype(jnp.int32)
    m_keep = os_ref.shape[1]
    os_ref[:] = jnp.stack(s[:m_keep], axis=0).T
    od_ref[:] = jnp.stack(d[:m_keep], axis=0).T
    ot_ref[:] = jnp.stack(t[:m_keep], axis=0).T
    nl_ref[:] = n_live[:, None]


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def sort_slots_pallas(score, dc, ts, m_keep: int, interpret: bool = False, tile: int = 2048):
    """Drop-in for `_sort_slots`: sort best-first, dedup, keep `m_keep`.

    Inputs [..., W] int32; returns (score, dc, ts)[..., :m_keep] and
    n_live[...] (live count before truncation)."""
    *lead, W = score.shape
    N = 1
    for x in lead:
        N *= x
    s2 = score.reshape(N, W)
    d2 = dc.reshape(N, W)
    t2 = ts.reshape(N, W)
    pad = (-N) % tile
    if pad:
        s2 = jnp.pad(s2, ((0, pad), (0, 0)), constant_values=NEG_INF)
        d2 = jnp.pad(d2, ((0, pad), (0, 0)))
        t2 = jnp.pad(t2, ((0, pad), (0, 0)))
    Np = N + pad
    grid = (Np // tile,)
    blk = lambda w: pl.BlockSpec((tile, w), lambda g: (g, 0))
    os_, od_, ot_, nl = pl.pallas_call(
        functools.partial(_sort_slots_kernel, W),
        grid=grid,
        in_specs=[blk(W), blk(W), blk(W)],
        out_specs=[blk(m_keep), blk(m_keep), blk(m_keep), blk(1)],
        out_shape=[
            jax.ShapeDtypeStruct((Np, m_keep), jnp.int32),
            jax.ShapeDtypeStruct((Np, m_keep), jnp.int32),
            jax.ShapeDtypeStruct((Np, m_keep), jnp.int32),
            jax.ShapeDtypeStruct((Np, 1), jnp.int32),
        ],
        interpret=interpret,
    )(s2, d2, t2)

    def back(x, w):
        return x[:N].reshape(*lead, w)

    return (
        back(os_, m_keep),
        back(od_, m_keep),
        back(ot_, m_keep),
        nl[:N, 0].reshape(lead),
    )


# --- tombstone row scatter-max --------------------------------------------


def _scatter_max_dma_kernel(B: int, idx_ref, tab_ref, upd_ref, out_ref, scratch, rd_sems, wr_sems):
    """Per-replica row read-modify-write loop with a 2-deep DMA pipeline.

    The table stays in HBM (unblocked); each row is DMA'd into a VMEM
    scratch slot, maxed with its (VMEM-resident) update, and DMA'd back.
    Row j+1's read overlaps row j's compute+write. Safe against duplicate
    rows because updates are idempotent-to-final (elementwise max with the
    run total) — even a torn concurrent read lands on the correct value."""
    r = pl.program_id(0)

    def rd(j, slot):
        return pltpu.make_async_copy(
            out_ref.at[r, pl.ds(idx_ref[r, j], 1), :], scratch.at[slot], rd_sems.at[slot]
        )

    def wr(j, slot):
        return pltpu.make_async_copy(
            scratch.at[slot], out_ref.at[r, pl.ds(idx_ref[r, j], 1), :], wr_sems.at[slot]
        )

    rd(0, 0).start()

    def body(j, carry):
        slot = jax.lax.rem(j, 2)

        # The write that last used slot 1-slot (iteration j-1) must land
        # before the next read overwrites that scratch buffer — otherwise
        # row idx[j-1] could be clobbered with row idx[j+1]'s raw contents
        # (cross-row corruption the idempotence argument does not cover).
        @pl.when((j + 1 < B) & (j >= 1))
        def _():
            wr(j - 1, 1 - slot).wait()

        @pl.when(j + 1 < B)
        def _():
            rd(j + 1, 1 - slot).start()

        rd(j, slot).wait()

        # scratch[slot]'s previous write (iteration j-2) needs no wait here:
        # it was already waited at iteration j-1's top (same slot algebra),
        # and waiting the same DMA semaphore twice would hang.
        scratch[slot] = jnp.maximum(scratch[slot], upd_ref[0, j][None, :])
        wr(j, slot).start()
        return carry

    jax.lax.fori_loop(0, B, body, 0)

    @pl.when(B >= 2)
    def _():
        wr(B - 2, jax.lax.rem(B - 2, 2)).wait()

    wr(B - 1, jax.lax.rem(B - 1, 2)).wait()


@functools.partial(jax.jit, static_argnums=(3,))
def scatter_max_rows_pallas(table, rows, upd, interpret: bool = False):
    """In-place `table.at[r, rows[r]].max(upd[r])` for non-negative updates.

    table [R, T, D] int32 (donated/aliased), rows [R, B] int32 in [0, T),
    upd [R, B, D] int32 >= 0. Duplicate rows are allowed ONLY if every
    duplicate carries the run's total (idempotent-to-final writes — use
    `combine_duplicate_rows`); otherwise the pipeline's stale
    read-modify-writes can drop updates."""
    R, T, D = table.shape
    _, B = rows.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(R,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # table (aliased, HBM)
            pl.BlockSpec((1, B, D), lambda r, idx: (r, 0, 0)),  # updates
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, 1, D), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_max_dma_kernel, B),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, T, D), jnp.int32),
        input_output_aliases={1: 0},
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(rows, table, upd)


# --- tiled one-hot MXU scatter-max ----------------------------------------


def _onehot_scatter_kernel(G, n_planes, D, Tt, rows_ref, planes_ref, tab_ref, out_ref):
    """One (replica, table-tile) step of the fused one-hot scatter-max.

    The [Br, T] one-hot that `ops.dense_table.scatter_max_rows_mxu`
    materializes in HBM (102MB per replica at Br=1024, T=100k — the
    dominant cost of the XLA version, ~15ms of the 40ms apply round) is
    instead generated tile-by-tile in VMEM, transposed, as
    ``ohT[t, b] = (rows[b] // G == tile_base + t)``: it exists only as an
    MXU operand and never touches HBM. The table rides in a [T//G, G*D]
    view so the minor dim is a 128-lane multiple (G=4, D=32) — the layout
    Mosaic rejected for the raw [T, 32] blocks — and the G-fold row packing
    also makes the one-hot G^2x smaller ([Br, T/G] vs [Br, T]).

    planes_ref carries the 7-bit value planes pre-spread to the row's
    G-slot (zero elsewhere), so each output cell still receives at most
    one nonzero term and s32 accumulation is exact (same argument as
    `scatter_max_rows_mxu`)."""
    rows = rows_ref[0, 0]  # [Br] i32, dedup'd run heads; sentinel >= T
    base = pl.program_id(1) * Tt
    local = (rows // G) - base  # target packed row, tile-local
    ohT = (
        jax.lax.broadcasted_iota(jnp.int32, (Tt, rows.shape[0]), 0)
        == local[None, :]
    ).astype(jnp.int8)
    acc = jax.lax.dot_general(
        ohT,
        planes_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Tt, G * n_planes * D]
    PD = n_planes * D
    cols = []
    for g in range(G):
        col = jnp.zeros((Tt, D), jnp.int32)
        for k in range(n_planes):
            col = col | (acc[:, g * PD + k * D : g * PD + (k + 1) * D] << (7 * k))
        cols.append(col)
    out_ref[0] = jnp.maximum(tab_ref[0], jnp.concatenate(cols, axis=-1))


@functools.partial(jax.jit, static_argnums=(3,))
def scatter_max_rows_onehot_pallas(table, rows, upd, interpret: bool = False):
    """Batched ``table[r].at[rows[r]].max(upd[r])`` for non-negative i32
    updates, with the one-hot generated tile-locally in VMEM.

    table [R, T, D] i32 (T % 4 == 0, D a multiple of 32... D=32 tested),
    rows [R, Br] i32 (>= T or negative = dropped), upd [R, Br, D] i32 >= 0.
    Duplicate rows allowed (dedup'd to run heads internally, as in
    `scatter_max_rows_mxu`).

    Status: verified infrastructure, NOT the production path. Honest v5e
    timings at [32, 100k, 32], Br=1024 (benchmarks/ablate_apply.py +
    micro_tombstone.py): in isolation ~13.5ms vs ~15.5ms for the XLA
    one-hot matmul — but composed with the rest of `apply_ops` the round
    regresses 40ms -> ~103ms, scan-fused AND fully unrolled alike, i.e.
    the custom call itself defeats XLA's cross-piece scheduling/fusion
    around it. Until that interaction is understood, the XLA path
    (`ops.dense_table.scatter_max_rows_mxu`) stays in production."""
    R, T, D = table.shape
    _, Br = rows.shape
    G = 4
    n_planes = 5
    assert T % G == 0, (T, G)
    T4 = T // G
    # Tile the packed-row axis: multiples of 8 sublanes; cover T4 exactly.
    Tt = 1000 if T4 % 1000 == 0 else (T4 if T4 <= 4096 else None)
    if Tt is None:
        for cand in (2048, 1024, 512, 256, 128, 64, 32, 16, 8):
            if T4 % cand == 0:
                Tt = cand
                break
        else:
            # No aligned tiling: fall back to the XLA path.
            f = jax.vmap(lambda t, r, u: dense_table.scatter_max_rows_mxu(t, r, u))
            return f(table, rows, upd)

    head_rows, total = jax.vmap(
        functools.partial(dense_table.dedup_rows_run_max, n_rows=T)
    )(rows, upd)
    # 7-bit planes spread to the row's G-slot: [R, Br, G * n_planes * D] s8.
    g_of = (head_rows % G)[..., None]  # [R, Br, 1]
    planes = jnp.concatenate(
        [((total >> (7 * k)) & 0x7F).astype(jnp.int8) for k in range(n_planes)],
        axis=-1,
    )  # [R, Br, n_planes*D]
    gsel = (
        g_of == jnp.arange(G, dtype=jnp.int32)[None, None, :]
    )  # [R, Br, G]
    planes_wide = jnp.where(
        gsel[..., :, None], planes[..., None, :], jnp.int8(0)
    ).reshape(R, Br, G * n_planes * D)

    tab4 = table.reshape(R, T4, G * D)
    out4 = pl.pallas_call(
        functools.partial(_onehot_scatter_kernel, G, n_planes, D, Tt),
        grid=(R, T4 // Tt),
        in_specs=[
            # rows ride with a unit sublane dim so the block's trailing two
            # dims (1, Br) equal the array dims (Mosaic's tiling rule).
            pl.BlockSpec((1, 1, Br), lambda r, t: (r, 0, 0)),
            pl.BlockSpec((1, Br, G * n_planes * D), lambda r, t: (r, 0, 0)),
            pl.BlockSpec((1, Tt, G * D), lambda r, t: (r, t, 0)),
        ],
        out_specs=pl.BlockSpec((1, Tt, G * D), lambda r, t: (r, t, 0)),
        out_shape=jax.ShapeDtypeStruct((R, T4, G * D), jnp.int32),
        interpret=interpret,
    )(head_rows[:, None, :], planes_wide, tab4)
    return out4.reshape(R, T, D)


def combine_duplicate_rows(rows, upd, n_rows: int):
    """Pre-pass for `scatter_max_rows_pallas`: make every write
    *idempotent-to-final*.

    Per replica, sort updates by row and give **each** entry of a duplicate
    run the run's total max (forward + backward segmented scans). Then any
    write order — including stale read-modify-writes from the kernel's
    software pipeline racing on a revisited row — lands on the correct
    final value, because max(anything_stale, total) == max(original,
    total). Padding (negative row) maps to row 0 carrying row 0's own
    total (or zero if row 0 is untouched), which is likewise idempotent.

    rows [R, B] int32 (negative = padding), upd [R, B, D] int32 >= 0.
    """
    R, B = rows.shape
    valid = rows >= 0
    key = jnp.where(valid, rows, jnp.int32(n_rows))  # padding sorts last
    order = jnp.argsort(key, axis=1)
    key_s = jnp.take_along_axis(key, order, axis=1)
    upd_s = jnp.take_along_axis(upd, order[..., None], axis=1)

    def seg(a, b):
        ka, va = a
        kb, vb = b
        same = (ka == kb)[..., None]
        return (kb, jnp.where(same, jnp.maximum(va, vb), vb))

    def seg_scan(keys, vals, reverse):
        kt = jnp.moveaxis(keys, 1, 0)
        vt = jnp.moveaxis(vals, 1, 0)
        if reverse:
            kt, vt = kt[::-1], vt[::-1]
        _, out = jax.lax.associative_scan(seg, (kt, vt), axis=0)
        if reverse:
            out = out[::-1]
        return jnp.moveaxis(out, 0, 1)

    fwd = seg_scan(key_s, upd_s, reverse=False)
    bwd = seg_scan(key_s, upd_s, reverse=True)
    total = jnp.maximum(fwd, bwd)  # run total at every element

    pad = key_s >= n_rows
    # Row 0's total (if updated) sits at sorted position 0.
    row0_total = jnp.where(
        (key_s[:, :1] == 0)[..., None], total[:, :1, :], 0
    )
    rows_out = jnp.where(pad, 0, key_s)
    upd_out = jnp.where(pad[..., None], row0_total, total)
    return rows_out, upd_out
