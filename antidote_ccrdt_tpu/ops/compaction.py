"""Batched op-log compaction: one vectorized pass over the whole log.

The reference compacts op logs *pairwise*: the host walks the log calling
``can_compact/2`` then ``compact_ops/2`` on adjacent pairs, with ``{noop}``
marking dead slots (richest rules in ``antidote_ccrdt_topk_rmv.erl:178-223``).
That protocol is inherently sequential — O(L) dependent steps per log, each
touching two ops. The TPU re-design compacts the *entire log in one dispatch*
(SURVEY.md §7 step 4): sort ops by (key, id), segmented reduce within each
group, rewrite tags, compress. The scalar pairwise protocol survives on the
``ScalarCCRDT`` types for parity; this module is what a host should actually
call.

Semantics preserved (differentially tested against scalar replay):

* **topk_rmv** (``topk_rmv.erl:197-223``): per (key, id) —
  - all removals fuse into ONE rmv op with the vc join of every removal vc
    (rmv/rmv rule :216-223); tagged ``rmv`` if any input was untagged
    (rmv absorbs rmv_r).
  - adds dominated by the fused tombstone (``vc[dc] >= ts``, :182-187) are
    deleted — exactly the adds ``update/2`` would reject. (Like the
    reference's add/rmv rule, this forgets the dominated add's clock
    advance; observable state is unaffected.)
  - exact duplicate adds (same score/dc/ts) are deduped (:255-259).
  - surviving adds keep the best ``m_keep`` per id by cmp order (score desc,
    ts desc); the winner carries the observable ``add`` tag iff any live add
    of the group was untagged, the rest are demoted to ``add_r``
    (add/add keep-best-demote-other, :198-202). ``update/2`` is
    tag-agnostic, so demotion never changes replayed state — tags only
    drive the host's shipping policy (``is_replicate_tagged``).

* **average** (``average.erl:127``): all adds per key fuse into one
  ``(sum, n)`` — the reference's perfect pairwise fusion, generalized.

* **topk**: adds per (key, id) keep the max score (fixing quirk #4: the
  reference's ``maps:merge`` is last-wins, ``topk.erl:160-161``).

* **wordcount/worddocumentcount**: counts fuse per (key, token) (fixing
  quirk #3: the reference *discards both ops*, ``wordcount.erl:70-72``).

All kernels are jit-compiled with static log length L; dead/padding rows are
pushed to the end and ``n_live`` reports the compacted length.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .dense_table import NEG_INF
from .segment import (
    prefix_rank as _prefix_rank,
    run_max as _run_max,
    segment_starts as _segment_starts,
)

# Op kinds for the dense topk_rmv log. DEAD marks padding on input and
# deleted slots on output (the reference's {noop}).
KIND_ADD = 0
KIND_ADD_R = 1
KIND_RMV = 2
KIND_RMV_R = 3
KIND_DEAD = 4

_BIG = np.int32(2**31 - 1)  # numpy: no backend init at import


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TopkRmvLog:
    """A dense effect-op log for topk_rmv instances on a [n_keys] grid.

    Row i is one effect op; ``kind == KIND_DEAD`` marks padding. ``vc`` is
    only meaningful for rmv rows (zeros otherwise); score/dc/ts only for
    adds.
    """

    kind: jax.Array  # i32[L]
    key: jax.Array  # i32[L] instance index
    id: jax.Array  # i32[L] element id
    score: jax.Array  # i32[L]
    dc: jax.Array  # i32[L]
    ts: jax.Array  # i32[L]
    vc: jax.Array  # i32[L, D]


def _compress(live: jax.Array, rows: Tuple[jax.Array, ...]):
    """Stable-partition live rows to the front. Returns (rows', n_live)."""
    order = jnp.argsort(~live, stable=True)
    return tuple(jnp.take(r, order, axis=0) for r in rows), jnp.sum(live)


def _compact_topk_rmv_sorted(log: TopkRmvLog, m_keep: int):
    """Shared core of the whole-log compaction: sort + group rules, WITHOUT
    the final compress. Returns the group-sorted field columns, the
    per-row fused vc for kept rmvs, and the live/kind masks — so each
    caller compacts into its own output shape with one partition instead
    of two.

    TPU notes (measured at the coalescing pass's L=147k x 32-replica
    shapes, where a first cut took ~2.5s):
    * group reductions are `run_max` doubling scans, never
      jax.ops.segment_max (XLA's serialized per-segment scatter);
    * the vc columns are gathered once by the sort permutation
      (`jnp.take(vc, row_s)` — ~200ms of row-gather at these shapes).
      Riding them through the main sort as 32 extra operands was tried
      and REJECTED: the 42-operand sort never finished remote-compiling
      (>9 min even at 4 replicas);
    * the per-row dc lookup into the fused vc is a one-hot reduce over D
      (cf. topk_rmv_dense._dom_lookup — minor-dim take_along_axis
      gathers are slow on TPU).
    """
    L, D = log.vc.shape
    is_add = (log.kind == KIND_ADD) | (log.kind == KIND_ADD_R)
    is_rmv = (log.kind == KIND_RMV) | (log.kind == KIND_RMV_R)
    dead = ~(is_add | is_rmv)

    # Sort: dead rows last; within a (key, id) group rmvs first, then adds
    # by cmp order desc (score, then ts — topk_rmv.erl:390-395). Non-add
    # rows sort with sanitized score/ts/dc (their values are meaningless
    # by the log contract), so a group's rmvs tie on those keys and land
    # at the group FRONT ordered by kind: the group's first row is a
    # complete has-rmv / observable-rmv summary.
    skey = jnp.where(dead, _BIG, log.key)
    sort_keys = (
        skey,
        jnp.where(dead, _BIG, log.id),
        is_add.astype(jnp.int32),
        jnp.where(is_add, -log.score, 0),
        jnp.where(is_add, -log.ts, 0),
        jnp.where(is_add, log.dc, 0),  # exact duplicates land adjacent
        log.kind,  # ...and among duplicates the observable add sorts
        # first, so dedup drops the add_r copy, not the add (:255-259);
        # among a group's rmvs the observable rmv sorts first.
    )
    payload = (
        log.score, log.ts, jnp.where(is_add, log.dc, 0),
        jnp.arange(L, dtype=jnp.int32),
    )
    sorted_all = lax.sort(sort_keys + payload, num_keys=7)
    key_s, id_s, _, _, _, _, kind_s, score_s, ts_s, dc_s, row_s = sorted_all
    is_add_s = (kind_s == KIND_ADD) | (kind_s == KIND_ADD_R)
    is_rmv_s = (kind_s == KIND_RMV) | (kind_s == KIND_RMV_R)
    vc_s = jnp.where(is_rmv_s[:, None], jnp.take(log.vc, row_s, axis=0), 0)

    first, start, seg = _segment_starts(key_s, id_s)

    # Fused tombstone per (key, id): vc join over the group's rmv rows
    # (merge_vcs, topk_rmv.erl:378-386), at every row of the group.
    merged_vc = _run_max(vc_s, seg)
    group_has_rmv = jnp.take(is_rmv_s, start)
    group_rmv_observable = jnp.take(kind_s, start) == KIND_RMV

    # Keep ONE rmv per group (the first), carrying the fused vc.
    rmv_rank = _prefix_rank(is_rmv_s, start)
    keep_rmv = is_rmv_s & (rmv_rank == 0)
    out_vc = jnp.where(keep_rmv[:, None], merged_vc, 0)

    # Adds: delete tombstone-dominated ones (vc[dc] >= ts, :182-187) and
    # exact duplicates (adjacent after the sort, :255-259).
    dom_at_dc = jnp.max(
        jnp.where(
            dc_s[:, None] == jnp.arange(D, dtype=dc_s.dtype)[None, :],
            merged_vc,
            0,
        ),
        axis=-1,
    )
    dom = dom_at_dc >= ts_s
    dup = (
        is_add_s
        & ~first
        & (jnp.roll(is_add_s, 1))
        & (score_s == jnp.roll(score_s, 1))
        & (ts_s == jnp.roll(ts_s, 1))
        & (dc_s == jnp.roll(dc_s, 1))
    )
    live_add = is_add_s & ~(group_has_rmv & dom) & ~dup
    add_rank = _prefix_rank(live_add, start)
    live_add = live_add & (add_rank < m_keep)

    # Tags: winner observable iff the group still ships an untagged add;
    # the rest demote to add_r (:198-202).
    group_has_obs_add = _run_max(
        (live_add & (kind_s == KIND_ADD)).astype(jnp.int32), seg
    ).astype(bool)
    add_kind = jnp.where(
        (add_rank == 0) & group_has_obs_add, KIND_ADD, KIND_ADD_R
    )
    rmv_kind = jnp.where(group_rmv_observable, KIND_RMV, KIND_RMV_R)

    live = live_add | keep_rmv
    out_kind = jnp.where(
        live_add, add_kind, jnp.where(keep_rmv, rmv_kind, KIND_DEAD)
    )
    return (
        out_kind, key_s, id_s, score_s, dc_s, ts_s, out_vc,
        live, live_add, keep_rmv,
    )


@functools.partial(jax.jit, static_argnums=(1,))
def compact_topk_rmv_log(log: TopkRmvLog, m_keep: int = 4):
    """Compact a topk_rmv effect log in one dispatch.

    Returns (compacted TopkRmvLog, n_live). Replaying the compacted log from
    any state yields the same observable state as the original log (modulo
    masked history beyond the best `m_keep` live adds per id — the same
    capacity bound as the dense state's M slots).
    """
    (
        out_kind, key_s, id_s, score_s, dc_s, ts_s, out_vc,
        live, _live_add, _keep_rmv,
    ) = _compact_topk_rmv_sorted(log, m_keep)
    (out_kind, key_o, id_o, score_o, dc_o, ts_o, vc_o), n_live = _compress(
        live, (out_kind, key_s, id_s, score_s, dc_s, ts_s, out_vc)
    )
    blank = out_kind == KIND_DEAD
    return (
        TopkRmvLog(
            kind=out_kind,
            key=jnp.where(blank, 0, key_o),
            id=jnp.where(blank, 0, id_o),
            score=jnp.where(blank, 0, score_o),
            dc=jnp.where(blank, 0, dc_o),
            ts=jnp.where(blank, 0, ts_o),
            vc=jnp.where(blank[:, None], 0, vc_o),
        ),
        n_live,
    )


@jax.jit
def compact_average_log(key: jax.Array, val: jax.Array, num: jax.Array):
    """Fuse every add per key into one (sum, n) op (average.erl:127).

    Padding: num <= 0 (the reference's N=0 no-op guard, average.erl:89).
    Returns (key', sum', n', n_live) with live rows first.
    """
    L = key.shape[0]
    pad = num <= 0
    skey = jnp.where(pad, _BIG, key)
    key_s, val_s, num_s = lax.sort((skey, val, num), num_keys=1)
    first, _, seg = _segment_starts(key_s)
    sums = jax.ops.segment_sum(
        jnp.where(key_s == _BIG, 0, val_s), seg, num_segments=L, indices_are_sorted=True
    )
    nums = jax.ops.segment_sum(
        jnp.where(key_s == _BIG, 0, num_s), seg, num_segments=L, indices_are_sorted=True
    )
    keep = first & (key_s != _BIG)
    out_val = jnp.where(keep, jnp.take(sums, seg), 0)
    out_num = jnp.where(keep, jnp.take(nums, seg), 0)
    (key_o, val_o, num_o), n_live = _compress(keep, (key_s, out_val, out_num))
    key_o = jnp.where(num_o > 0, key_o, 0)
    return key_o, val_o, num_o, n_live


@jax.jit
def compact_topk_log(key: jax.Array, id_: jax.Array, score: jax.Array):
    """One add per (key, id), keeping the MAX score (fixes quirk #4 — the
    reference merges duplicate ids last-wins, topk.erl:160-161).

    Padding: score < 0. Returns (key', id', score', n_live), live first.
    """
    pad = score < 0
    skey = jnp.where(pad, _BIG, key)
    key_s, id_s, nscore = lax.sort((skey, id_, -score), num_keys=3)
    score_s = -nscore
    first, _, _ = _segment_starts(key_s, id_s)
    keep = first & (key_s != _BIG)
    (key_o, id_o, score_o), n_live = _compress(keep, (key_s, id_s, score_s))
    blank = jnp.arange(key.shape[0]) >= n_live
    return (
        jnp.where(blank, 0, key_o),
        jnp.where(blank, 0, id_o),
        jnp.where(blank, -1, score_o),
        n_live,
    )


# Op kinds for the dense leaderboard log.
KIND_LB_ADD = 0
KIND_LB_ADD_R = 1
KIND_LB_BAN = 2
KIND_LB_DEAD = 3


@jax.jit
def compact_leaderboard_log(
    kind: jax.Array, key: jax.Array, id_: jax.Array, score: jax.Array
):
    """Compact a leaderboard effect log in one dispatch.

    The reference's pairwise rules (``leaderboard.erl:163-205``): add/add of
    the same player keep the better score (the winner keeps its own tag);
    an add followed by a ban of that player deletes the add; ban/ban of the
    same player dedupe. The whole-log pass additionally drops *every* add
    of a player the log also bans regardless of order — sound because bans
    are permanent (``leaderboard.erl:21-27``) and the ban rides the same
    compacted log, so replay at any replica reaches the same state; the
    pairwise protocol cannot see that because it only looks forward.

    Tags: among equal best scores the observable ``add`` is preferred over
    ``add_r`` so compaction never downgrades the host's shipping decision.

    Padding: kind == KIND_LB_DEAD. Returns (kind', key', id', score',
    n_live) with live rows first.
    """
    L = key.shape[0]
    is_add = (kind == KIND_LB_ADD) | (kind == KIND_LB_ADD_R)
    is_ban = kind == KIND_LB_BAN
    dead = ~(is_add | is_ban)

    skey = jnp.where(dead, _BIG, key)
    # Sort: dead last; per (key, id) bans first, then adds best-first
    # (score desc, observable tag before add_r on ties).
    sort_keys = (
        skey,
        jnp.where(dead, _BIG, id_),
        is_add.astype(jnp.int32),
        -score,
        kind,
    )
    key_s, id_s, _, nscore_s, kind_s = lax.sort(sort_keys, num_keys=5)
    score_s = -nscore_s
    is_add_s = (kind_s == KIND_LB_ADD) | (kind_s == KIND_LB_ADD_R)
    is_ban_s = kind_s == KIND_LB_BAN

    first, start, seg = _segment_starts(key_s, id_s)
    group_has_ban = jnp.take(
        jax.ops.segment_max(
            is_ban_s.astype(jnp.int32), seg, num_segments=L, indices_are_sorted=True
        ),
        seg,
    ).astype(bool)

    ban_rank = _prefix_rank(is_ban_s, start)
    keep_ban = is_ban_s & (ban_rank == 0)
    add_rank = _prefix_rank(is_add_s, start)
    keep_add = is_add_s & (add_rank == 0) & ~group_has_ban

    live = keep_ban | keep_add
    out_kind = jnp.where(live, kind_s, KIND_LB_DEAD)
    (kind_o, key_o, id_o, score_o), n_live = _compress(
        live, (out_kind, key_s, id_s, score_s)
    )
    blank = kind_o == KIND_LB_DEAD
    return (
        kind_o,
        jnp.where(blank, 0, key_o),
        jnp.where(blank, 0, id_o),
        jnp.where(blank, 0, score_o),
        n_live,
    )


@jax.jit
def compact_wordcount_log(key: jax.Array, token: jax.Array, count: jax.Array):
    """Fuse counts per (key, token) (fixes quirk #3 — the reference's
    compact_ops discards both ops, wordcount.erl:70-72).

    Padding: token < 0. Returns (key', token', count', n_live), live first.
    """
    L = key.shape[0]
    pad = token < 0
    skey = jnp.where(pad, _BIG, key)
    key_s, tok_s, cnt_s = lax.sort((skey, token, count), num_keys=2)
    first, _, seg = _segment_starts(key_s, tok_s)
    sums = jax.ops.segment_sum(
        jnp.where(key_s == _BIG, 0, cnt_s), seg, num_segments=L, indices_are_sorted=True
    )
    keep = first & (key_s != _BIG)
    out_cnt = jnp.where(keep, jnp.take(sums, seg), 0)
    (key_o, tok_o, cnt_o), n_live = _compress(keep, (key_s, tok_s, out_cnt))
    blank = jnp.arange(L) >= n_live
    return (
        jnp.where(blank, 0, key_o),
        jnp.where(blank, -1, tok_o),
        jnp.where(blank, 0, cnt_o),
        n_live,
    )


# --- term-level entry: host effect logs in, compacted logs out -------------
#
# The production surface VERDICT r3 flagged as missing: the reference's
# host compacts its op log through `can_compact/2` + `compact_ops/2`
# (antidote_ccrdt.erl:55-56) before shipping; this is the whole-log
# vectorized equivalent operating directly on the scalar effect-op tuples
# a host holds ("add"/"add_r"/"rmv"/"rmv_r"/"ban"/"add_counts" + payload,
# exactly the shapes `ScalarCCRDT.update` consumes). Exposed over the
# bridge wire as the `grid_compact` op (bridge/server.py) and used by the
# batch coalescers below.


def _round_up(n: int, q: int = 64) -> int:
    return max(q, (n + q - 1) // q * q)


def compact_effect_ops(type_name, effects, m_keep=None):
    """Compact a list of scalar effect-op tuples for `type_name` in one
    vectorized pass. Returns the compacted list (order: the kernel's
    (key, id) grouping, observable tags preserved per the reference's
    pairwise rules — see the per-type kernels above).

    `m_keep` bounds surviving adds per id for topk_rmv (None = keep every
    non-dominated add, the reference-compaction semantics: its add/add
    rule demotes but never deletes, topk_rmv.erl:198-202)."""
    known = ("topk_rmv", "average", "topk", "leaderboard",
             "wordcount", "worddocumentcount")
    if type_name not in known:
        raise ValueError(f"no whole-log compactor for type {type_name!r}")
    effects = list(effects)
    if not effects:
        return []
    if type_name == "topk_rmv":
        return _compact_effects_topk_rmv(effects, m_keep)
    if type_name == "average":
        return _compact_effects_average(effects)
    if type_name == "topk":
        return _compact_effects_topk(effects)
    if type_name == "leaderboard":
        return _compact_effects_leaderboard(effects)
    return _compact_effects_wordcount(type_name, effects)


def _compact_effects_topk_rmv(effects, m_keep):
    kinds = {"add": KIND_ADD, "add_r": KIND_ADD_R, "rmv": KIND_RMV, "rmv_r": KIND_RMV_R}
    L = _round_up(len(effects))
    max_dc = 0
    for kind, payload in effects:
        if kind not in kinds:
            raise ValueError(f"bad topk_rmv effect kind {kind!r}")
        if kind in ("add", "add_r"):
            max_dc = max(max_dc, int(payload[2][0]))
        else:
            vc = payload[1]
            if vc:
                max_dc = max(max_dc, max(int(d) for d in vc))
    D = max_dc + 1
    log = TopkRmvLog(
        kind=np.full(L, KIND_DEAD, np.int32),
        key=np.zeros(L, np.int32),
        id=np.zeros(L, np.int32),
        score=np.zeros(L, np.int32),
        dc=np.zeros(L, np.int32),
        ts=np.zeros(L, np.int32),
        vc=np.zeros((L, D), np.int32),
    )
    for j, (kind, payload) in enumerate(effects):
        log.kind[j] = kinds[kind]
        if kind in ("add", "add_r"):
            id_, score, (dc, ts) = payload
            log.id[j], log.score[j] = id_, score
            log.dc[j], log.ts[j] = dc, ts
        else:
            id_, vc = payload
            log.id[j] = id_
            for d, t in vc.items():
                log.vc[j, int(d)] = t
    jlog = jax.tree.map(jnp.asarray, log)
    out, n_live = compact_topk_rmv_log(jlog, m_keep if m_keep is not None else L)
    out = jax.tree.map(np.asarray, out)
    res = []
    for j in range(int(n_live)):
        k = int(out.kind[j])
        if k in (KIND_ADD, KIND_ADD_R):
            res.append(
                ("add" if k == KIND_ADD else "add_r",
                 (int(out.id[j]), int(out.score[j]),
                  (int(out.dc[j]), int(out.ts[j]))))
            )
        else:
            vc = {int(d): int(t) for d, t in enumerate(out.vc[j]) if t > 0}
            res.append(("rmv" if k == KIND_RMV else "rmv_r", (int(out.id[j]), vc)))
    return res


def _compact_effects_average(effects):
    L = _round_up(len(effects))
    key = np.zeros(L, np.int32)
    val = np.zeros(L, np.int32)
    num = np.zeros(L, np.int32)
    for j, (kind, payload) in enumerate(effects):
        if kind != "add":
            raise ValueError(f"bad average effect kind {kind!r}")
        v, n = (payload if isinstance(payload, tuple) else (payload, 1))
        val[j], num[j] = v, n
    _, val_o, num_o, n_live = compact_average_log(
        jnp.asarray(key), jnp.asarray(val), jnp.asarray(num)
    )
    return [
        ("add", (int(val_o[j]), int(num_o[j]))) for j in range(int(n_live))
    ]


def _compact_effects_topk(effects):
    L = _round_up(len(effects))
    key = np.zeros(L, np.int32)
    id_ = np.zeros(L, np.int32)
    score = np.full(L, -1, np.int32)
    for j, (kind, payload) in enumerate(effects):
        if kind != "add":
            raise ValueError(f"bad topk effect kind {kind!r}")
        id_[j], score[j] = payload
    _, id_o, score_o, n_live = compact_topk_log(
        jnp.asarray(key), jnp.asarray(id_), jnp.asarray(score)
    )
    return [("add", (int(id_o[j]), int(score_o[j]))) for j in range(int(n_live))]


def _compact_effects_leaderboard(effects):
    kinds = {"add": KIND_LB_ADD, "add_r": KIND_LB_ADD_R, "ban": KIND_LB_BAN}
    names = {KIND_LB_ADD: "add", KIND_LB_ADD_R: "add_r", KIND_LB_BAN: "ban"}
    L = _round_up(len(effects))
    kind = np.full(L, KIND_LB_DEAD, np.int32)
    key = np.zeros(L, np.int32)
    id_ = np.zeros(L, np.int32)
    score = np.zeros(L, np.int32)
    for j, (k, payload) in enumerate(effects):
        if k not in kinds:
            raise ValueError(f"bad leaderboard effect kind {k!r}")
        kind[j] = kinds[k]
        if k == "ban":
            id_[j] = payload
        else:
            id_[j], score[j] = payload
    kind_o, _, id_o, score_o, n_live = compact_leaderboard_log(
        jnp.asarray(kind), jnp.asarray(key), jnp.asarray(id_), jnp.asarray(score)
    )
    res = []
    for j in range(int(n_live)):
        k = int(kind_o[j])
        if k == KIND_LB_BAN:
            res.append(("ban", int(id_o[j])))
        else:
            res.append((names[k], (int(id_o[j]), int(score_o[j]))))
    return res


def _compact_effects_wordcount(type_name, effects):
    """Wordcount family: each effect contributes per-token counts (texts
    tokenize; worddocumentcount dedupes tokens PER DOCUMENT first —
    wordcount.erl:76-86 via models.wordcount semantics), then counts fuse
    per token through the dense kernel over a local token index."""
    from ..models.wordcount import tokenize

    per_document = type_name == "worddocumentcount"
    contribs = []  # (token string, count)
    for kind, payload in effects:
        if kind == "add":
            toks = tokenize(payload)
            if per_document:
                toks = set(toks)
            for w in toks:
                contribs.append((w, 1))
        elif kind == "add_counts":
            contribs.extend((w, int(c)) for w, c in payload.items())
        else:
            raise ValueError(f"bad {type_name} effect kind {kind!r}")
    if not contribs:
        return []
    vocab = {}
    for w, _ in contribs:
        vocab.setdefault(w, len(vocab))
    words = list(vocab)
    L = _round_up(len(contribs))
    key = np.zeros(L, np.int32)
    tok = np.full(L, -1, np.int32)
    cnt = np.zeros(L, np.int32)
    for j, (w, c) in enumerate(contribs):
        tok[j], cnt[j] = vocab[w], c
    _, tok_o, cnt_o, n_live = compact_wordcount_log(
        jnp.asarray(key), jnp.asarray(tok), jnp.asarray(cnt)
    )
    merged = {
        words[int(tok_o[j])]: int(cnt_o[j]) for j in range(int(n_live))
    }
    return [("add_counts", merged)] if merged else []


# --- batch coalescing: the replay/pipeline pre-ship pass -------------------


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _coalesce_topk_rmv_kernel(log: TopkRmvLog, m_keep: int, out_adds: int, out_rmvs: int):
    """vmapped over replicas: compact one [L] log and re-split it into
    fixed-shape add/rmv op fields (dead rows -> the engines' padding
    sentinels: add_ts=0, rmv_id=-1)."""

    def one(lg):
        (
            _out_kind, key_s, id_s, score_s, dc_s, ts_s, out_vc,
            _live, live_add, keep_rmv,
        ) = _compact_topk_rmv_sorted(lg, m_keep)
        # Stable-partition each class to the front, then SLICE the output
        # window — takes of out_adds/out_rmvs rows straight from the
        # group-sorted columns (no intermediate full-log compress; a first
        # cut scattered all L rows into the windows, which XLA's
        # serialized scatter loop made ~200ms at north-star shapes). Rows
        # taken beyond the class count are non-class rows; mask them back
        # to the engines' padding sentinels (add_ts=0 / rmv_id=-1).
        order_a = jnp.argsort(~live_add, stable=True)[:out_adds]
        a_ok = jnp.take(live_add, order_a)

        def pick_a(x, empty):
            return jnp.where(a_ok, jnp.take(x, order_a), empty)

        add_key = pick_a(key_s, 0)
        add_id = pick_a(id_s, 0)
        add_score = pick_a(score_s, 0)
        add_dc = pick_a(dc_s, 0)
        add_ts = pick_a(ts_s, 0)
        n_add = jnp.sum(live_add)

        order_r = jnp.argsort(~keep_rmv, stable=True)[:out_rmvs]
        r_ok = jnp.take(keep_rmv, order_r)
        rmv_key = jnp.where(r_ok, jnp.take(key_s, order_r), 0)
        rmv_id = jnp.where(r_ok, jnp.take(id_s, order_r), -1)
        rmv_vc = jnp.where(
            r_ok[:, None], jnp.take(out_vc, order_r, axis=0), 0
        )
        n_rmv = jnp.sum(keep_rmv)
        return (
            (add_key, add_id, add_score, add_dc, add_ts),
            (rmv_key, rmv_id, rmv_vc),
            n_add, n_rmv,
        )

    return jax.vmap(one)(log)


def coalesce_topk_rmv_ops(ops_list, n_dcs: int, m_keep: int,
                          out_adds: int, out_rmvs: int):
    """Fuse a sequence of TopkRmvOps batches into ONE compacted batch — the
    pre-ship pass over op logs (reference: the host compacts its log
    before shipping, antidote_ccrdt.erl:55-56; rules
    antidote_ccrdt_topk_rmv.erl:178-223). Removals fuse per id, dominated
    and duplicate adds are deleted, surviving adds keep the best `m_keep`
    per id (match the engine's slot capacity M: the join truncates there
    anyway, so compaction at M loses nothing the state would keep —
    batches that overflow M set `lossy` either way).

    Returns (TopkRmvOps[R, out_adds / out_rmvs], n_add[R], n_rmv[R]).
    Raises if any replica's live ops overflow the output windows.

    Semantics note (same divergence the reference accepts): a dominated
    add deleted by compaction no longer advances the state vc
    (topk_rmv.erl:182-187 'forgets the clock advance'), and it can no
    longer be reported as a dominated extra — run compaction on logs
    whose dominated re-broadcasts are not needed (e.g. intra-DC replay),
    not between `downstream` and the extras-collecting apply.
    """
    from ..models.topk_rmv_dense import TopkRmvOps

    ops_list = list(ops_list)
    cat = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=1), *ops_list)
    R = cat.add_key.shape[0]
    Ba, Brr = cat.add_key.shape[1], cat.rmv_key.shape[1]
    L = _round_up(Ba + Brr, 128)
    pad_a = L - Ba - Brr

    add_kind = jnp.where(cat.add_ts > 0, KIND_ADD, KIND_DEAD)
    rmv_kind = jnp.where(cat.rmv_id >= 0, KIND_RMV, KIND_DEAD)

    def cat_field(a_val, r_val, pad_val):
        return jnp.concatenate(
            [a_val, r_val,
             jnp.full((R, pad_a) + a_val.shape[2:], pad_val, a_val.dtype)],
            axis=1,
        )

    if cat.rmv_vc.shape[-1] != n_dcs:
        raise ValueError(
            f"rmv_vc width {cat.rmv_vc.shape[-1]} != n_dcs {n_dcs}"
        )
    log = TopkRmvLog(
        kind=cat_field(add_kind, rmv_kind, KIND_DEAD),
        key=cat_field(cat.add_key, cat.rmv_key, 0),
        id=cat_field(cat.add_id, cat.rmv_id, 0),
        score=cat_field(cat.add_score, jnp.zeros_like(cat.rmv_key), 0),
        dc=cat_field(cat.add_dc, jnp.zeros_like(cat.rmv_key), 0),
        ts=cat_field(cat.add_ts, jnp.zeros_like(cat.rmv_key), 0),
        vc=cat_field(
            jnp.zeros(cat.add_key.shape + (n_dcs,), jnp.int32), cat.rmv_vc, 0
        ),
    )
    (a_fields, r_fields, n_add, n_rmv) = _coalesce_topk_rmv_kernel(
        log, m_keep, out_adds, out_rmvs
    )
    n_add_h, n_rmv_h = np.asarray(n_add), np.asarray(n_rmv)
    if (n_add_h > out_adds).any() or (n_rmv_h > out_rmvs).any():
        raise ValueError(
            f"coalesced log overflows output windows: max {int(n_add_h.max())} "
            f"adds / {int(n_rmv_h.max())} rmvs vs ({out_adds}, {out_rmvs})"
        )
    add_key, add_id, add_score, add_dc, add_ts = a_fields
    rmv_key, rmv_id, rmv_vc = r_fields
    return (
        TopkRmvOps(
            add_key=add_key, add_id=add_id, add_score=add_score,
            add_dc=add_dc, add_ts=add_ts,
            rmv_key=rmv_key, rmv_id=rmv_id, rmv_vc=rmv_vc,
        ),
        n_add_h, n_rmv_h,
    )


# -- wire-window delta coalescing (ingest fast path) ------------------------
# The gossip analog of the pre-ship op pass above: fuse K consecutive
# pending publish windows' deltas into ONE frame. Every gossip delta ships
# row/cell VALUES under an idempotent join (topk_rmv slot rows, table JOIN
# cells, lifted-monoid versioned rows), so last-window-wins per touched
# row is exact: the coalesced frame produces the bit-identical state the
# K chained frames would have. (MONOID table *diffs* — which never ride
# gossip; the lift replaces them with versioned rows — sum instead.)
# Host-side numpy: window row counts differ every publish, and the frame
# is serialized to bytes immediately after (same reasoning as
# parallel.delta.state_delta).


def _last_wins(rows_cat: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(unique_rows_sorted, gather_index_of_LAST_occurrence). The inputs
    are concatenated in window order, so "last occurrence" is "latest
    window" — the join-exact winner for value-shipping deltas."""
    rev = rows_cat[::-1]
    uniq, first_rev = np.unique(rev, return_index=True)
    return uniq, rows_cat.shape[0] - 1 - first_rev


def coalesce_topk_rmv_deltas(deltas):
    """Fuse K chained `parallel.delta.TopkRmvDelta` windows (oldest
    first) into one delta: union of touched rows, latest window's payload
    per row, latest whole-state leaves (vc/lossy are monotone and each
    window ships them in full)."""
    from ..parallel.delta import TopkRmvDelta

    deltas = list(deltas)
    if len(deltas) == 1:
        return deltas[0]
    rows_cat = np.concatenate([np.asarray(d.rows) for d in deltas])
    uniq, take = _last_wins(rows_cat)

    def cat(field):
        return np.concatenate([np.asarray(getattr(d, field)) for d in deltas])

    return TopkRmvDelta(
        rows=jnp.asarray(uniq.astype(np.int32)),
        slot_score=jnp.asarray(cat("slot_score")[take]),
        slot_dc=jnp.asarray(cat("slot_dc")[take]),
        slot_ts=jnp.asarray(cat("slot_ts")[take]),
        rmv_vc=jnp.asarray(cat("rmv_vc")[take]),
        vc=deltas[-1].vc,
        lossy=deltas[-1].lossy,
    )


def coalesce_table_deltas(deltas, monoid: bool = False):
    """Fuse K chained entrywise table deltas (`parallel.delta.table_delta`
    dicts, oldest first). JOIN payloads: latest value per touched cell +
    latest whole leaves. MONOID payloads ship diffs — sum per cell, and
    sum the integer whole leaves (the non-integer ones ship values)."""
    deltas = list(deltas)
    if len(deltas) == 1:
        return deltas[0]
    idx_cat = np.concatenate([np.asarray(d["idx"]) for d in deltas])
    table_paths = list(deltas[-1]["table"])
    out_table = {}
    if monoid:
        uniq = np.unique(idx_cat)
        pos = {int(v): i for i, v in enumerate(uniq)}
        scatter = np.asarray([pos[int(v)] for v in idx_cat], np.int64)
        for p in table_paths:
            vals = np.concatenate([np.asarray(d["table"][p]) for d in deltas])
            acc = np.zeros(uniq.shape[0], vals.dtype)
            np.add.at(acc, scatter, vals)
            out_table[p] = jnp.asarray(acc)
    else:
        uniq, take = _last_wins(idx_cat)
        for p in table_paths:
            vals = np.concatenate([np.asarray(d["table"][p]) for d in deltas])
            out_table[p] = jnp.asarray(vals[take])
    out_whole = {}
    for p, last in deltas[-1]["whole"].items():
        if monoid and np.issubdtype(np.asarray(last).dtype, np.integer):
            out_whole[p] = jnp.asarray(
                sum(np.asarray(d["whole"][p]) for d in deltas)
            )
        else:
            out_whole[p] = last
    return {
        "idx": jnp.asarray(uniq.astype(np.int32)),
        "table": out_table,
        "whole": out_whole,
    }


def coalesce_deltas(dense, deltas):
    """Engine-generic fuse of K chained gossip deltas (oldest first), or
    None when this delta flavor has no coalesce kernel (lifted-monoid row
    deltas — the publisher falls back to re-cutting the interval delta
    against the last shipped state, which is exact for every engine)."""
    from ..core.behaviour import MergeKind
    from ..parallel.delta import TopkRmvDelta, _is_monoid_row_delta

    deltas = list(deltas)
    if not deltas:
        return None
    if all(isinstance(d, TopkRmvDelta) for d in deltas):
        return coalesce_topk_rmv_deltas(deltas)
    if all(
        isinstance(d, dict) and not _is_monoid_row_delta(d) and "idx" in d
        for d in deltas
    ):
        monoid = getattr(dense, "merge_kind", None) == MergeKind.MONOID
        return coalesce_table_deltas(deltas, monoid=monoid)
    return None
