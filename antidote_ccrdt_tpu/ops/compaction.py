"""Batched op-log compaction: one vectorized pass over the whole log.

The reference compacts op logs *pairwise*: the host walks the log calling
``can_compact/2`` then ``compact_ops/2`` on adjacent pairs, with ``{noop}``
marking dead slots (richest rules in ``antidote_ccrdt_topk_rmv.erl:178-223``).
That protocol is inherently sequential — O(L) dependent steps per log, each
touching two ops. The TPU re-design compacts the *entire log in one dispatch*
(SURVEY.md §7 step 4): sort ops by (key, id), segmented reduce within each
group, rewrite tags, compress. The scalar pairwise protocol survives on the
``ScalarCCRDT`` types for parity; this module is what a host should actually
call.

Semantics preserved (differentially tested against scalar replay):

* **topk_rmv** (``topk_rmv.erl:197-223``): per (key, id) —
  - all removals fuse into ONE rmv op with the vc join of every removal vc
    (rmv/rmv rule :216-223); tagged ``rmv`` if any input was untagged
    (rmv absorbs rmv_r).
  - adds dominated by the fused tombstone (``vc[dc] >= ts``, :182-187) are
    deleted — exactly the adds ``update/2`` would reject. (Like the
    reference's add/rmv rule, this forgets the dominated add's clock
    advance; observable state is unaffected.)
  - exact duplicate adds (same score/dc/ts) are deduped (:255-259).
  - surviving adds keep the best ``m_keep`` per id by cmp order (score desc,
    ts desc); the winner carries the observable ``add`` tag iff any live add
    of the group was untagged, the rest are demoted to ``add_r``
    (add/add keep-best-demote-other, :198-202). ``update/2`` is
    tag-agnostic, so demotion never changes replayed state — tags only
    drive the host's shipping policy (``is_replicate_tagged``).

* **average** (``average.erl:127``): all adds per key fuse into one
  ``(sum, n)`` — the reference's perfect pairwise fusion, generalized.

* **topk**: adds per (key, id) keep the max score (fixing quirk #4: the
  reference's ``maps:merge`` is last-wins, ``topk.erl:160-161``).

* **wordcount/worddocumentcount**: counts fuse per (key, token) (fixing
  quirk #3: the reference *discards both ops*, ``wordcount.erl:70-72``).

All kernels are jit-compiled with static log length L; dead/padding rows are
pushed to the end and ``n_live`` reports the compacted length.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .dense_table import NEG_INF
from .segment import prefix_rank as _prefix_rank, segment_starts as _segment_starts

# Op kinds for the dense topk_rmv log. DEAD marks padding on input and
# deleted slots on output (the reference's {noop}).
KIND_ADD = 0
KIND_ADD_R = 1
KIND_RMV = 2
KIND_RMV_R = 3
KIND_DEAD = 4

_BIG = np.int32(2**31 - 1)  # numpy: no backend init at import


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TopkRmvLog:
    """A dense effect-op log for topk_rmv instances on a [n_keys] grid.

    Row i is one effect op; ``kind == KIND_DEAD`` marks padding. ``vc`` is
    only meaningful for rmv rows (zeros otherwise); score/dc/ts only for
    adds.
    """

    kind: jax.Array  # i32[L]
    key: jax.Array  # i32[L] instance index
    id: jax.Array  # i32[L] element id
    score: jax.Array  # i32[L]
    dc: jax.Array  # i32[L]
    ts: jax.Array  # i32[L]
    vc: jax.Array  # i32[L, D]


def _compress(live: jax.Array, rows: Tuple[jax.Array, ...]):
    """Stable-partition live rows to the front. Returns (rows', n_live)."""
    order = jnp.argsort(~live, stable=True)
    return tuple(jnp.take(r, order, axis=0) for r in rows), jnp.sum(live)


@functools.partial(jax.jit, static_argnums=(1,))
def compact_topk_rmv_log(log: TopkRmvLog, m_keep: int = 4):
    """Compact a topk_rmv effect log in one dispatch.

    Returns (compacted TopkRmvLog, n_live). Replaying the compacted log from
    any state yields the same observable state as the original log (modulo
    masked history beyond the best `m_keep` live adds per id — the same
    capacity bound as the dense state's M slots).
    """
    L, D = log.vc.shape
    is_add = (log.kind == KIND_ADD) | (log.kind == KIND_ADD_R)
    is_rmv = (log.kind == KIND_RMV) | (log.kind == KIND_RMV_R)
    dead = ~(is_add | is_rmv)

    # Sort: dead rows last; within a (key, id) group rmvs first, then adds
    # by cmp order desc (score, then ts — topk_rmv.erl:390-395).
    skey = jnp.where(dead, _BIG, log.key)
    sort_keys = (
        skey,
        jnp.where(dead, _BIG, log.id),
        is_add.astype(jnp.int32),
        -log.score,
        -log.ts,
        log.dc,  # exact duplicates must land adjacent for the dedup pass
        log.kind,  # ...and among duplicates the observable add sorts first,
        # so dedup drops the add_r copy, not the add (:255-259)
    )
    payload = (log.score, log.ts, jnp.arange(L, dtype=jnp.int32))
    sorted_all = lax.sort(sort_keys + payload, num_keys=7)
    key_s, id_s, _, _, _, dc_s, kind_s, score_s, ts_s, row_s = sorted_all
    vc_s = jnp.take(log.vc, row_s, axis=0)
    dead_s = kind_s == KIND_DEAD
    is_add_s = (kind_s == KIND_ADD) | (kind_s == KIND_ADD_R)
    is_rmv_s = (kind_s == KIND_RMV) | (kind_s == KIND_RMV_R)

    first, start, seg = _segment_starts(key_s, id_s)

    # Fused tombstone per (key, id): vc join over the group's rmv rows
    # (merge_vcs, topk_rmv.erl:378-386).
    rmv_vc_rows = jnp.where(is_rmv_s[:, None], vc_s, 0)
    seg_vc = jax.ops.segment_max(
        rmv_vc_rows, seg, num_segments=L, indices_are_sorted=True
    )
    merged_vc = jnp.take(seg_vc, seg, axis=0)  # [L, D] per-row group vc
    group_has_rmv = jnp.take(
        jax.ops.segment_max(
            is_rmv_s.astype(jnp.int32), seg, num_segments=L, indices_are_sorted=True
        ),
        seg,
    ).astype(bool)
    group_rmv_observable = jnp.take(
        jax.ops.segment_max(
            (kind_s == KIND_RMV).astype(jnp.int32),
            seg,
            num_segments=L,
            indices_are_sorted=True,
        ),
        seg,
    ).astype(bool)

    # Keep ONE rmv per group (the first), carrying the fused vc.
    rmv_rank = _prefix_rank(is_rmv_s, start)
    keep_rmv = is_rmv_s & (rmv_rank == 0)
    out_vc = jnp.where(keep_rmv[:, None], merged_vc, 0)

    # Adds: delete tombstone-dominated ones (vc[dc] >= ts, :182-187) and
    # exact duplicates (adjacent after the sort, :255-259).
    dom = (
        jnp.take_along_axis(merged_vc, jnp.clip(dc_s, 0, D - 1)[:, None], axis=1)[:, 0]
        >= ts_s
    )
    dup = (
        is_add_s
        & ~first
        & (jnp.roll(is_add_s, 1))
        & (score_s == jnp.roll(score_s, 1))
        & (ts_s == jnp.roll(ts_s, 1))
        & (dc_s == jnp.roll(dc_s, 1))
    )
    live_add = is_add_s & ~(group_has_rmv & dom) & ~dup
    add_rank = _prefix_rank(live_add, start)
    live_add = live_add & (add_rank < m_keep)

    # Tags: winner observable iff the group still ships an untagged add;
    # the rest demote to add_r (:198-202).
    group_has_obs_add = jnp.take(
        jax.ops.segment_max(
            (live_add & (kind_s == KIND_ADD)).astype(jnp.int32),
            seg,
            num_segments=L,
            indices_are_sorted=True,
        ),
        seg,
    ).astype(bool)
    add_kind = jnp.where(
        (add_rank == 0) & group_has_obs_add, KIND_ADD, KIND_ADD_R
    )
    rmv_kind = jnp.where(group_rmv_observable, KIND_RMV, KIND_RMV_R)

    live = live_add | keep_rmv
    out_kind = jnp.where(
        live_add, add_kind, jnp.where(keep_rmv, rmv_kind, KIND_DEAD)
    )

    (out_kind, key_o, id_o, score_o, dc_o, ts_o, vc_o), n_live = _compress(
        live, (out_kind, key_s, id_s, score_s, dc_s, ts_s, out_vc)
    )
    blank = out_kind == KIND_DEAD
    return (
        TopkRmvLog(
            kind=out_kind,
            key=jnp.where(blank, 0, key_o),
            id=jnp.where(blank, 0, id_o),
            score=jnp.where(blank, 0, score_o),
            dc=jnp.where(blank, 0, dc_o),
            ts=jnp.where(blank, 0, ts_o),
            vc=jnp.where(blank[:, None], 0, vc_o),
        ),
        n_live,
    )


@jax.jit
def compact_average_log(key: jax.Array, val: jax.Array, num: jax.Array):
    """Fuse every add per key into one (sum, n) op (average.erl:127).

    Padding: num <= 0 (the reference's N=0 no-op guard, average.erl:89).
    Returns (key', sum', n', n_live) with live rows first.
    """
    L = key.shape[0]
    pad = num <= 0
    skey = jnp.where(pad, _BIG, key)
    key_s, val_s, num_s = lax.sort((skey, val, num), num_keys=1)
    first, _, seg = _segment_starts(key_s)
    sums = jax.ops.segment_sum(
        jnp.where(key_s == _BIG, 0, val_s), seg, num_segments=L, indices_are_sorted=True
    )
    nums = jax.ops.segment_sum(
        jnp.where(key_s == _BIG, 0, num_s), seg, num_segments=L, indices_are_sorted=True
    )
    keep = first & (key_s != _BIG)
    out_val = jnp.where(keep, jnp.take(sums, seg), 0)
    out_num = jnp.where(keep, jnp.take(nums, seg), 0)
    (key_o, val_o, num_o), n_live = _compress(keep, (key_s, out_val, out_num))
    key_o = jnp.where(num_o > 0, key_o, 0)
    return key_o, val_o, num_o, n_live


@jax.jit
def compact_topk_log(key: jax.Array, id_: jax.Array, score: jax.Array):
    """One add per (key, id), keeping the MAX score (fixes quirk #4 — the
    reference merges duplicate ids last-wins, topk.erl:160-161).

    Padding: score < 0. Returns (key', id', score', n_live), live first.
    """
    pad = score < 0
    skey = jnp.where(pad, _BIG, key)
    key_s, id_s, nscore = lax.sort((skey, id_, -score), num_keys=3)
    score_s = -nscore
    first, _, _ = _segment_starts(key_s, id_s)
    keep = first & (key_s != _BIG)
    (key_o, id_o, score_o), n_live = _compress(keep, (key_s, id_s, score_s))
    blank = jnp.arange(key.shape[0]) >= n_live
    return (
        jnp.where(blank, 0, key_o),
        jnp.where(blank, 0, id_o),
        jnp.where(blank, -1, score_o),
        n_live,
    )


# Op kinds for the dense leaderboard log.
KIND_LB_ADD = 0
KIND_LB_ADD_R = 1
KIND_LB_BAN = 2
KIND_LB_DEAD = 3


@jax.jit
def compact_leaderboard_log(
    kind: jax.Array, key: jax.Array, id_: jax.Array, score: jax.Array
):
    """Compact a leaderboard effect log in one dispatch.

    The reference's pairwise rules (``leaderboard.erl:163-205``): add/add of
    the same player keep the better score (the winner keeps its own tag);
    an add followed by a ban of that player deletes the add; ban/ban of the
    same player dedupe. The whole-log pass additionally drops *every* add
    of a player the log also bans regardless of order — sound because bans
    are permanent (``leaderboard.erl:21-27``) and the ban rides the same
    compacted log, so replay at any replica reaches the same state; the
    pairwise protocol cannot see that because it only looks forward.

    Tags: among equal best scores the observable ``add`` is preferred over
    ``add_r`` so compaction never downgrades the host's shipping decision.

    Padding: kind == KIND_LB_DEAD. Returns (kind', key', id', score',
    n_live) with live rows first.
    """
    L = key.shape[0]
    is_add = (kind == KIND_LB_ADD) | (kind == KIND_LB_ADD_R)
    is_ban = kind == KIND_LB_BAN
    dead = ~(is_add | is_ban)

    skey = jnp.where(dead, _BIG, key)
    # Sort: dead last; per (key, id) bans first, then adds best-first
    # (score desc, observable tag before add_r on ties).
    sort_keys = (
        skey,
        jnp.where(dead, _BIG, id_),
        is_add.astype(jnp.int32),
        -score,
        kind,
    )
    key_s, id_s, _, nscore_s, kind_s = lax.sort(sort_keys, num_keys=5)
    score_s = -nscore_s
    is_add_s = (kind_s == KIND_LB_ADD) | (kind_s == KIND_LB_ADD_R)
    is_ban_s = kind_s == KIND_LB_BAN

    first, start, seg = _segment_starts(key_s, id_s)
    group_has_ban = jnp.take(
        jax.ops.segment_max(
            is_ban_s.astype(jnp.int32), seg, num_segments=L, indices_are_sorted=True
        ),
        seg,
    ).astype(bool)

    ban_rank = _prefix_rank(is_ban_s, start)
    keep_ban = is_ban_s & (ban_rank == 0)
    add_rank = _prefix_rank(is_add_s, start)
    keep_add = is_add_s & (add_rank == 0) & ~group_has_ban

    live = keep_ban | keep_add
    out_kind = jnp.where(live, kind_s, KIND_LB_DEAD)
    (kind_o, key_o, id_o, score_o), n_live = _compress(
        live, (out_kind, key_s, id_s, score_s)
    )
    blank = kind_o == KIND_LB_DEAD
    return (
        kind_o,
        jnp.where(blank, 0, key_o),
        jnp.where(blank, 0, id_o),
        jnp.where(blank, 0, score_o),
        n_live,
    )


@jax.jit
def compact_wordcount_log(key: jax.Array, token: jax.Array, count: jax.Array):
    """Fuse counts per (key, token) (fixes quirk #3 — the reference's
    compact_ops discards both ops, wordcount.erl:70-72).

    Padding: token < 0. Returns (key', token', count', n_live), live first.
    """
    L = key.shape[0]
    pad = token < 0
    skey = jnp.where(pad, _BIG, key)
    key_s, tok_s, cnt_s = lax.sort((skey, token, count), num_keys=2)
    first, _, seg = _segment_starts(key_s, tok_s)
    sums = jax.ops.segment_sum(
        jnp.where(key_s == _BIG, 0, cnt_s), seg, num_segments=L, indices_are_sorted=True
    )
    keep = first & (key_s != _BIG)
    out_cnt = jnp.where(keep, jnp.take(sums, seg), 0)
    (key_o, tok_o, cnt_o), n_live = _compress(keep, (key_s, tok_s, out_cnt))
    blank = jnp.arange(L) >= n_live
    return (
        jnp.where(blank, 0, key_o),
        jnp.where(blank, -1, tok_o),
        jnp.where(blank, 0, cnt_o),
        n_live,
    )
