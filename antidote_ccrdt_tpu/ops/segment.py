"""Sort/segment primitives shared by the dense CRDT kernels.

Everything here is shaped for XLA on TPU: multi-key lexicographic sorts via
``lax.sort(num_keys=...)``, group boundaries / ranks via roll-compare and
cumulative max — no data-dependent shapes, no scatter conflicts.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def segment_starts(
    *keys: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Group structure of *already sorted* 1-D key columns.

    Elements of one group (equal on every key) must be contiguous. Returns
    ``(first, start, seg)``: per-row first-in-group flag, index of the
    group's first row, and dense segment id (0, 1, 2, ... — usable as
    ``segment_sum``/``segment_max`` ids with ``num_segments=len``).
    """
    n = keys[0].shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    first = jnp.zeros(n, dtype=bool)
    for k in keys:
        first = first | (k != jnp.roll(k, 1, axis=0))
    first = first.at[0].set(True)
    start = lax.cummax(jnp.where(first, idx, 0))
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    return first, start, seg


def prefix_rank(flag: jax.Array, start: jax.Array) -> jax.Array:
    """Rank of each True `flag` row among the True rows of its segment
    (segments given by per-row group-start indices from segment_starts)."""
    excl = jnp.cumsum(flag.astype(jnp.int32)) - flag.astype(jnp.int32)
    return excl - jnp.take(excl, start)


def group_rank(group_keys: Sequence[jax.Array]) -> jax.Array:
    """Rank of each element within its group, for *already sorted* inputs:
    int32 ranks 0,1,2,... restarting at each group boundary."""
    n = group_keys[0].shape[0]
    _, start, _ = segment_starts(*group_keys)
    return jnp.arange(n, dtype=jnp.int32) - start
