"""Sort/segment primitives shared by the dense CRDT kernels.

Everything here is shaped for XLA on TPU: multi-key lexicographic sorts via
``lax.sort(num_keys=...)``, ranks within sorted groups via cumulative max —
no data-dependent shapes, no scatter conflicts.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def group_rank(group_keys: Sequence[jax.Array]) -> jax.Array:
    """Rank of each element within its group, for *already sorted* inputs.

    `group_keys` are 1-D arrays that jointly identify the group (e.g. (key,
    id)); elements of one group must be contiguous. Returns int32 ranks
    0,1,2,... restarting at each group boundary.
    """
    n = group_keys[0].shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    first = jnp.zeros(n, dtype=bool).at[0].set(True)
    for k in group_keys:
        first = first | (k != jnp.roll(k, 1))
    first = first.at[0].set(True)
    # Position of each element's group start: running max of start indices.
    start = lax.cummax(jnp.where(first, idx, 0))
    return idx - start
