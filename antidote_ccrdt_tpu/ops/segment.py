"""Sort/segment primitives shared by the dense CRDT kernels.

Everything here is shaped for XLA on TPU: multi-key lexicographic sorts via
``lax.sort(num_keys=...)``, group boundaries / ranks via roll-compare and
cumulative max — no data-dependent shapes, no scatter conflicts.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def segment_starts(
    *keys: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Group structure of *already sorted* 1-D key columns.

    Elements of one group (equal on every key) must be contiguous. Returns
    ``(first, start, seg)``: per-row first-in-group flag, index of the
    group's first row, and dense segment id (0, 1, 2, ... — usable as
    ``segment_sum``/``segment_max`` ids with ``num_segments=len``).
    """
    n = keys[0].shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    first = jnp.zeros(n, dtype=bool)
    for k in keys:
        first = first | (k != jnp.roll(k, 1, axis=0))
    first = first.at[0].set(True)
    start = lax.cummax(jnp.where(first, idx, 0))
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    return first, start, seg


def prefix_rank(flag: jax.Array, start: jax.Array) -> jax.Array:
    """Rank of each True `flag` row among the True rows of its segment
    (segments given by per-row group-start indices from segment_starts)."""
    excl = jnp.cumsum(flag.astype(jnp.int32)) - flag.astype(jnp.int32)
    return excl - jnp.take(excl, start)


def run_max(vals: jax.Array, seg: jax.Array, direction: str = "both") -> jax.Array:
    """Per-row max over the row's segment, for *already sorted* segment
    ids: ``out[i] = max(vals[j] for j where seg[j] == seg[i])``.

    `direction`: "both" covers the whole segment; "prefix" covers only
    [segment start, i]; "suffix" only [i, segment end] — each saves half
    the doubling traffic when the caller's data makes one side enough
    (e.g. the consumer sits at the segment boundary).

    Non-negative values only (0 is the shift identity). `vals` is [L] or
    [L, D] (the segment axis is 0); `seg` is the dense [L] segment id from
    `segment_starts`.

    log2(L) prefix-doubling + log2(L) suffix-doubling steps of fused
    shift/where chains — NOT ``jax.ops.segment_max``, which lowers to
    XLA's serialized per-segment scatter loop on TPU: at the coalescing
    pass's shapes (L=147k x 32 replicas) the four segment_max calls in
    `compact_topk_rmv_log` cost ~2.5s; this formulation runs the same
    reductions in milliseconds. Correctness: segments are contiguous, so
    ``seg[i] == seg[i-k]`` implies the whole [i-k, i] span is one
    segment; after the stride-k step the accumulator covers a 2k window
    clipped to the segment, and prefix+suffix windows jointly cover the
    entire run."""
    L = seg.shape[0]
    lift = (lambda m: m[:, None]) if vals.ndim == 2 else (lambda m: m)

    def shifted(arr, k, fill):
        pad = jnp.full((k,) + arr.shape[1:], fill, arr.dtype)
        return (
            jnp.concatenate([pad, arr[:-k]], axis=0),
            jnp.concatenate([arr[k:], pad], axis=0),
        )

    want_pre = direction in ("both", "prefix")
    want_suf = direction in ("both", "suffix")
    assert want_pre or want_suf, direction
    pre = vals
    suf = vals
    k = 1
    while k < L:
        seg_b, seg_f = shifted(seg, k, -1)
        if want_pre:
            pre_b, _ = shifted(pre, k, 0)
            pre = jnp.where(lift(seg == seg_b), jnp.maximum(pre, pre_b), pre)
        if want_suf:
            _, suf_f = shifted(suf, k, 0)
            suf = jnp.where(lift(seg == seg_f), jnp.maximum(suf, suf_f), suf)
        k *= 2
    if not want_suf:
        return pre
    if not want_pre:
        return suf
    return jnp.maximum(pre, suf)


def group_rank(group_keys: Sequence[jax.Array]) -> jax.Array:
    """Rank of each element within its group, for *already sorted* inputs:
    int32 ranks 0,1,2,... restarting at each group boundary."""
    n = group_keys[0].shape[0]
    _, start, _ = segment_starts(*group_keys)
    return jnp.arange(n, dtype=jnp.int32) - start
