"""Delta-table placement as a Mosaic carry-walk kernel (round 5).

The delta build in `TopkRmvDense._apply_one_replica` places B sorted adds
into three [NK*I, M] tables at (kid, rank) — XLA lowers this to a
serialized scalar-scatter loop (~15.4ms of the ~53.5ms apply round at
north-star shapes pre-r5, ~9.7ms with the r5 sorted/unique hints; the
HBM bytes floor of the same writes is ~0.4ms). Structural replacement:

1. Output address ``o = kid*M + rank`` is UNIQUE and STRICTLY INCREASING
   over kept entries (kid nondecreasing from the shared sort; rank
   increments within a group). A cheap 1-key compaction sort by ``o``
   pushes the non-kept entries (o = sentinel) to the stream tail.
2. After compaction, the entries targeting any 128-address output block
   are at most 128 CONSECUTIVE stream positions — so a kernel can walk
   the stream with a carried scalar offset per replica, with no
   data-dependent gathers, no searchsorted, and no unbounded spans.
3. Per 128-address sub-block: one [128, 128] iota-compare one-hot and
   one s8 MXU matmul against 16 seven-bit value planes (score rides
   u32-wrapped against its NEG_INF background so unwritten cells decode
   to NEG_INF with zero masking; ts 5 planes; dc 1 plane, D <= 128).
   Each output cell receives at most one nonzero term (o unique), so
   s32 accumulation is exact — the `scatter_max_rows_mxu` argument
   (ops/dense_table.py) applied to placement.

Semantics replaced: the three `.at[kid, rank].set` scatters of
`models/topk_rmv_dense.py` step 3 (reference update/2,
antidote_ccrdt_topk_rmv.erl:231-249 batch analog). Equivalence is pinned
by tests/test_pallas_kernels.py and benchmarks/delta_place_probe.py.

Status: verified infrastructure, NOT the production path. Correct on
first TPU compile (probe equivalence OK at full north-star shapes), but
measured 57.2 ms/round vs 24.3 for the unique-hint XLA scatters
(benchmarks/delta_place_probe.py, REPS=12): the per-sub-block fixed
costs — 4 tiny dynamic VMEM loads x ~3,125 sub-blocks x 32 replicas,
plus the SMEM carry serializing consecutive grid steps — dominate; the
design is load-latency-bound, not flop-bound, and growing GROUP only
converges to ~14-16ms. The probe docstring carries the full verdict.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .dense_table import NEG_INF

SB = 128       # addresses per sub-block (= one one-hot / matmul)
GROUP = 4096   # addresses per grid step (SB * sub-blocks per step)


def _carry_walk_kernel(
    B, n_sub, o_ref, sc_ref, dc_ref, ts_ref, os_ref, od_ref, ot_ref, carry_ref
):
    g = pl.program_id(1)

    @pl.when(g == 0)
    def _():
        carry_ref[0] = 0

    carry = carry_ref[0]
    base = g * GROUP
    # Window of GROUP+SB stream entries starting at (the 128-aligned floor
    # of) the first unconsumed one — Mosaic requires dynamic lane-dim
    # offsets provably 128-aligned, and `(x // SB) * SB` is. All entries
    # consumable this step lie in [carry, carry+GROUP) (their addresses
    # are unique within a GROUP-address range), so the widened window
    # covers them; entries before `carry` (alignment slack or the tail
    # clamp) are excluded by the jvalid position mask.
    WEXT = GROUP + SB
    st = ((jnp.minimum(carry, B - WEXT) // SB) * SB)
    o_w = o_ref[0, 0, pl.ds(st, WEXT)]
    jpos = st + lax.broadcasted_iota(jnp.int32, (1, WEXT), 1)[0]
    jvalid = jpos >= carry
    consumable = jvalid & (o_w < base + GROUP)

    for sb in range(n_sub):
        sub_base = base + sb * SB
        # First stream position targeting this sub-block = carry + count
        # of consumable entries below it (they are consecutive). The load
        # is floored to the 128-aligned slot and widened to 2*SB; the
        # alignment-slack entries need no mask — anything before the true
        # offset has o < sub_base and anything beyond the sub-block's run
        # has o >= sub_base+SB, so the one-hot's local-range compare
        # drops both.
        nb = jnp.sum((consumable & (o_w < sub_base)).astype(jnp.int32))
        off = ((jnp.minimum(carry + nb, B - 2 * SB) // SB) * SB)
        o2 = o_ref[0, 0, pl.ds(off, 2 * SB)]
        sc2 = sc_ref[0, 0, pl.ds(off, 2 * SB)]
        dc2 = dc_ref[0, 0, pl.ds(off, 2 * SB)]
        ts2 = ts_ref[0, 0, pl.ds(off, 2 * SB)]

        local = o2 - sub_base  # stale -> <0, later/sentinel -> >=SB
        oh = (
            lax.broadcasted_iota(jnp.int32, (SB, 2 * SB), 0) == local[None, :]
        ).astype(jnp.int8)  # [addr, j]

        # 16 rows of 7-bit planes: score (u32-wrapped against NEG_INF so
        # zero accumulation decodes to the background), ts, dc, zero pad.
        diff = sc2 - NEG_INF  # i32 wrap == u32 subtraction bits
        rows = [((diff >> (7 * k)) & 0x7F).astype(jnp.int8) for k in range(5)]
        rows += [((ts2 >> (7 * k)) & 0x7F).astype(jnp.int8) for k in range(5)]
        rows += [(dc2 & 0x7F).astype(jnp.int8)]
        rows += [jnp.zeros((2 * SB,), jnp.int8)] * 5
        planes_t = jnp.stack(rows, axis=0)  # [16, 2*SB]

        acc = lax.dot_general(
            oh, planes_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [SB addr, 16]

        def bits(c0):
            v = acc[:, c0]
            for k in range(1, 5):
                v = v | (acc[:, c0 + k] << (7 * k))
            return v

        # Output blocks are [1, 1, 8, GROUP//8] (Mosaic's trailing-dims
        # tiling rule); the sub-block's 128 addresses land at row sb//4,
        # columns (sb%4)*SB.. — flattening [8, GROUP//8] row-major
        # reproduces base + sb*SB + a exactly.
        row, cs = sb // 4, (sb % 4) * SB
        sl = (0, 0, row, slice(cs, cs + SB))
        os_ref[sl] = bits(0) + NEG_INF
        ot_ref[sl] = bits(5)
        od_ref[sl] = acc[:, 10]

    carry_ref[0] = carry + jnp.sum(consumable.astype(jnp.int32))


@functools.partial(jax.jit, static_argnums=(6, 7, 8, 9))
def delta_place_pallas(
    s_score, s_ts, s_dc, kid3, rank, keep, T, M, D, interpret: bool = False
):
    """Build (d_score[R,T,M], d_dc, d_ts) from the sorted add stream.

    Inputs are the per-replica outputs of the shared sort+rank stage
    ([R, B] each): kid3 (nondecreasing; sentinel T for dead entries),
    rank in [0, M) for kept entries, keep marking the entries to place.
    Exact same tables as the production 3-scatter build.
    """
    assert D <= 128, "dc rides a single 7-bit plane; D > 128 unsupported"
    R, B = kid3.shape
    OUT = T * M
    assert OUT < 2**30, "address space must leave sentinel headroom"
    NG = -(-OUT // GROUP)
    OUTP = NG * GROUP
    SENT = jnp.int32(OUTP)  # beyond every block: never matched or consumed

    o = jnp.where(keep, kid3 * M + rank, SENT)
    if B < GROUP + SB:  # tiny shapes: pad the stream with sentinels
        pad = GROUP + SB - B
        o = jnp.pad(o, ((0, 0), (0, pad)), constant_values=OUTP)
        s_score = jnp.pad(s_score, ((0, 0), (0, pad)))
        s_dc = jnp.pad(s_dc, ((0, 0), (0, pad)))
        s_ts = jnp.pad(s_ts, ((0, 0), (0, pad)))
        B = GROUP + SB
    o_s, sc_s, dc_s, ts_s = jax.vmap(
        lambda *a: lax.sort(a, num_keys=1)
    )(o, s_score, s_dc, s_ts)

    # Streams ride with a unit sublane dim so the block's trailing two
    # dims (1, B) equal the array dims (Mosaic's tiling rule); outputs
    # are [NG, 8, GROUP//8] per replica so trailing block dims divide
    # (8, 128).
    spec_in = pl.BlockSpec((1, 1, B), lambda r, g: (r, 0, 0))
    spec_out = pl.BlockSpec((1, 1, 8, GROUP // 8), lambda r, g: (r, g, 0, 0))
    out3 = pl.pallas_call(
        functools.partial(_carry_walk_kernel, B, GROUP // SB),
        grid=(R, NG),
        in_specs=[spec_in] * 4,
        out_specs=[spec_out] * 3,
        out_shape=[jax.ShapeDtypeStruct((R, NG, 8, GROUP // 8), jnp.int32)] * 3,
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(*(x[:, None, :] for x in (o_s, sc_s, dc_s, ts_s)))
    d_score, d_dc, d_ts = (
        x.reshape(R, OUTP)[:, :OUT].reshape(R, T, M) for x in out3
    )
    return d_score, d_dc, d_ts