"""Batched lattice-law kernels + per-type law fixtures (the audit plane's
compute tier).

Certified-MRDT-style machine checking (PAPERS.md: arxiv 2203.14518) of the
algebraic laws every replication mechanism in this repo leans on:

* merge commutativity + associativity for EVERY registered dense type;
* merge idempotence for JOIN types (MONOID states are deltas — merging a
  delta with itself legitimately double-counts, so idempotence is not a
  law there; the gossip tier ships monoid state through the versioned
  `MonoidLift` rows instead);
* delta composition: ``apply_any_delta(dense, prev, make_delta(dense,
  prev, cur)) == cur`` for a chained (prev, cur) pair — the exact
  invariant `sweep_deltas` relies on when it chains a peer's delta
  stream.

Batching: a fixture generates states with a [1, n] instance grid (each
key cell an independently-reached instance), so one ``merge`` dispatch
checks n instance pairs and one tree-compare dispatch reduces them —
checking thousands of pairs costs a handful of XLA dispatches, not
thousands of Python loops.

Fixtures are registered on the central type registry
(`core.behaviour.Registry.register(law_fixture=...)`) so new types can
ship their own reachable-state generators; this module registers
generators for the six built-in types at import time. States MUST come
from real op applications — random leaf noise would violate engine
invariants (sorted slots, masked sets) and fail laws that in fact hold
on every reachable state.

`BrokenMergeDense` is the committed negative fixture: a deliberately
non-commutative merge the checker must flag (the audit CLI's
``laws --selftest`` and tests/test_audit.py both require it to FAIL).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.behaviour import MergeKind, registry


# -- batched tree comparison -------------------------------------------------


@jax.jit
def _tree_eq(a: Any, b: Any) -> jax.Array:
    eqs = [
        jnp.all(x == y)
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        )
    ]
    if not eqs:
        return jnp.asarray(True)
    return jnp.all(jnp.stack(eqs))


def tree_equal(a: Any, b: Any) -> bool:
    """Exact leaf-wise equality of two identically-shaped pytrees, reduced
    on device to one scalar."""
    return bool(_tree_eq(a, b))


def instance_mismatch(a: Any, b: Any) -> np.ndarray:
    """bool [R, NK] per-instance mismatch mask: every leaf reduced over
    its trailing axes onto the leading instance grid (leaves without the
    grid — there are none on DenseCCRDT states, but fixtures may carry
    them — broadcast into every cell)."""
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    grid: Optional[Tuple[int, int]] = next(
        (tuple(x.shape[:2]) for x in leaves_a if getattr(x, "ndim", 0) >= 2),
        None,
    )
    if grid is None:
        ne = any(not bool(jnp.all(x == y)) for x, y in zip(leaves_a, leaves_b))
        return np.asarray([[ne]])
    mask = np.zeros(grid, bool)
    for x, y in zip(leaves_a, leaves_b):
        ne = np.asarray(x != y)
        if ne.ndim >= 2 and ne.shape[:2] == grid:
            mask |= ne.reshape(grid[0], grid[1], -1).any(axis=-1)
        elif ne.any():
            mask |= True
    return mask


# -- law checking ------------------------------------------------------------


def check_engine_laws(
    dense: Any, states: List[Any], chain: Optional[Tuple[Any, Any]] = None
) -> Dict[str, Any]:
    """Machine-check the merge laws for one engine on >= 3 batched states.

    The verdict uses the engine's OWN equality (`dense.equal`) when it
    has one — topk_rmv's slot planes are canonical up to the engine's
    equality, not bit order — and exact tree equality otherwise. The
    per-instance failure count (for counterexamples) always comes from
    the tree mismatch mask, so a failing law names the first bad
    (replica, key) cell."""
    a, b, c = states[0], states[1], states[2]
    merge = jax.jit(dense.merge)
    eng_eq = getattr(dense, "equal", None)

    def equal(x: Any, y: Any) -> bool:
        return bool(eng_eq(x, y)) if eng_eq is not None else tree_equal(x, y)

    ab = merge(a, b)
    pairs: Dict[str, Tuple[Any, Any]] = {
        "commutativity": (ab, merge(b, a)),
        "associativity": (merge(ab, c), merge(a, merge(b, c))),
    }
    if dense.merge_kind == MergeKind.JOIN:
        pairs["idempotence"] = (merge(a, a), a)
    if chain is not None:
        from ..parallel.delta import apply_any_delta, make_delta

        prev, cur = chain
        pairs["delta_composition"] = (
            apply_any_delta(dense, prev, make_delta(dense, prev, cur)), cur
        )

    n_instances = int(np.prod(
        jax.tree_util.tree_leaves(a)[0].shape[:2]
    ))
    laws: Dict[str, Any] = {}
    for law, (x, y) in pairs.items():
        ok = equal(x, y)
        entry: Dict[str, Any] = {"ok": ok, "instances": n_instances}
        if not ok:
            mask = instance_mismatch(x, y)
            bad = np.argwhere(mask)
            entry["failed_instances"] = int(mask.sum())
            if len(bad):
                entry["first_failure_rk"] = [int(v) for v in bad[0]]
        laws[law] = entry
    return {
        "type": getattr(dense, "type_name", type(dense).__name__),
        "merge_kind": dense.merge_kind.value,
        "n_instances": n_instances,
        "laws": laws,
        "ok": all(e["ok"] for e in laws.values()),
    }


# -- built-in fixtures -------------------------------------------------------
#
# fixture(seed, n) -> {"dense": engine, "states": [A, B, C], "chain":
# (prev, cur) | None}; every state is a [1, n] instance grid built by
# applying a seeded op batch, so all n pairs are reachable.


def _fx_topk(seed: int, n: int) -> Dict[str, Any]:
    from ..models import topk as tk

    d = tk.make_dense(n_ids=24, size=4)

    def gen(s: int, nb: int = 4) -> Any:
        rng = np.random.default_rng(1000 * (seed + 1) + s)
        bsz = nb * n
        ops = tk.TopkOps(
            key=jnp.asarray(rng.integers(0, n, bsz).astype(np.int32)[None]),
            id=jnp.asarray(rng.integers(0, 24, bsz).astype(np.int32)[None]),
            score=jnp.asarray(
                rng.integers(1, 500, bsz).astype(np.int32)[None]
            ),
            valid=jnp.asarray(np.ones(bsz, bool)[None]),
        )
        return ops

    def st(s: int) -> Any:
        out, _ = d.apply_ops(d.init(1, n), gen(s))
        return out

    prev = st(0)
    cur, _ = d.apply_ops(prev, gen(7))
    return {
        "dense": d, "states": [st(0), st(1), st(2)], "chain": (prev, cur),
    }


def _fx_leaderboard(seed: int, n: int) -> Dict[str, Any]:
    from ..models import leaderboard as lb

    d = lb.make_dense(n_players=24, size=4)

    def gen(s: int) -> Any:
        rng = np.random.default_rng(2000 * (seed + 1) + s)
        bsz, bb = 4 * n, max(4, n // 2)
        return lb.LeaderboardOps(
            add_key=jnp.asarray(rng.integers(0, n, bsz).astype(np.int32)[None]),
            add_id=jnp.asarray(rng.integers(0, 24, bsz).astype(np.int32)[None]),
            add_score=jnp.asarray(
                rng.integers(1, 500, bsz).astype(np.int32)[None]
            ),
            add_valid=jnp.asarray(np.ones(bsz, bool)[None]),
            ban_key=jnp.asarray(rng.integers(0, n, bb).astype(np.int32)[None]),
            ban_id=jnp.asarray(rng.integers(0, 24, bb).astype(np.int32)[None]),
            ban_valid=jnp.asarray((rng.random(bb) < 0.5)[None]),
        )

    def st(s: int) -> Any:
        out, _ = d.apply_ops(d.init(1, n), gen(s))
        return out

    prev = st(0)
    cur, _ = d.apply_ops(prev, gen(7))
    return {
        "dense": d, "states": [st(0), st(1), st(2)], "chain": (prev, cur),
    }


def _fx_wordcount(name: str):
    def fixture(seed: int, n: int) -> Dict[str, Any]:
        from ..models import wordcount as wc

        d = wc.make_dense(n_buckets=32)

        def gen(s: int) -> Any:
            rng = np.random.default_rng(3000 * (seed + 1) + s)
            bsz = 6 * n
            # Tokens beyond the table (>= 32) exercise the lost-counter
            # monoid leaf too.
            return wc.WordcountOps(
                key=jnp.asarray(
                    rng.integers(0, n, bsz).astype(np.int32)[None]
                ),
                token=jnp.asarray(
                    rng.integers(0, 40, bsz).astype(np.int32)[None]
                ),
            )

        def st(s: int) -> Any:
            out, _ = d.apply_ops(d.init(1, n), gen(s))
            return out

        prev = st(0)
        cur, _ = d.apply_ops(prev, gen(7))
        return {
            "dense": d, "states": [st(0), st(1), st(2)],
            "chain": (prev, cur),
        }

    return fixture


def _fx_average(seed: int, n: int) -> Dict[str, Any]:
    from ..models.average import AverageDense, AverageOps

    d = AverageDense()

    def gen(s: int) -> Any:
        rng = np.random.default_rng(4000 * (seed + 1) + s)
        bsz = 4 * n
        return AverageOps(
            key=jnp.asarray(rng.integers(0, n, bsz).astype(np.int32)[None]),
            value=jnp.asarray(
                rng.integers(-50, 50, bsz).astype(np.int32)[None]
            ),
            count=jnp.asarray(rng.integers(0, 5, bsz).astype(np.int32)[None]),
        )

    def st(s: int) -> Any:
        out, _ = d.apply_ops(d.init(1, n), gen(s))
        return out

    prev = st(0)
    cur, _ = d.apply_ops(prev, gen(7))
    return {
        "dense": d, "states": [st(0), st(1), st(2)], "chain": (prev, cur),
    }


def _fx_topk_rmv(seed: int, n: int) -> Dict[str, Any]:
    from ..models.topk_rmv_dense import TopkRmvOps, make_dense

    i_, dcs = 16, 3
    d = make_dense(n_ids=i_, n_dcs=dcs, size=4, slots_per_id=3)

    def gen(s: int) -> Any:
        rng = np.random.default_rng(5000 * (seed + 1) + s)
        bsz, br = 4 * n, max(4, n // 2)
        r_vc = np.zeros((1, br, dcs), np.int32)
        r_vc[0, :, rng.integers(0, dcs)] = rng.integers(1, 200, br)
        return TopkRmvOps(
            add_key=jnp.asarray(rng.integers(0, n, bsz).astype(np.int32)[None]),
            add_id=jnp.asarray(rng.integers(0, i_, bsz).astype(np.int32)[None]),
            add_score=jnp.asarray(
                rng.integers(1, 500, bsz).astype(np.int32)[None]
            ),
            add_dc=jnp.asarray(
                rng.integers(0, dcs, bsz).astype(np.int32)[None]
            ),
            add_ts=jnp.asarray(
                rng.integers(1, 1000, bsz).astype(np.int32)[None]
            ),
            rmv_key=jnp.asarray(rng.integers(0, n, br).astype(np.int32)[None]),
            rmv_id=jnp.asarray(rng.integers(0, i_, br).astype(np.int32)[None]),
            rmv_vc=jnp.asarray(r_vc),
        )

    def st(s: int) -> Any:
        out, _ = d.apply_ops(d.init(1, n), gen(s), collect_dominated=False)
        return out

    prev = st(0)
    cur, _ = d.apply_ops(prev, gen(7), collect_dominated=False)
    return {
        "dense": d, "states": [st(0), st(1), st(2)], "chain": (prev, cur),
    }


# -- the committed negative fixture ------------------------------------------


class BrokenMergeDense:
    """A deliberately NON-commutative, NON-associative 'engine' whose
    merge is ``2a - b``. It is idempotent (``2a - a == a``) on purpose:
    the checker must flag the specific broken laws, not just any law.
    Never registered on the global registry — it enters a run only via
    `LawChecker(extra_fixtures=...)` / ``ccrdt_audit.py laws --selftest``."""

    type_name = "broken_merge_fixture"
    merge_kind = MergeKind.JOIN

    def init(self, n_replicas: int, n_keys: int) -> Dict[str, jax.Array]:
        return {"x": jnp.zeros((n_replicas, n_keys), jnp.int32)}

    def merge(
        self, a: Dict[str, jax.Array], b: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        return {"x": 2 * a["x"] - b["x"]}


def broken_merge_fixture(seed: int, n: int) -> Dict[str, Any]:
    d = BrokenMergeDense()

    def st(lo: int, hi: int) -> Dict[str, jax.Array]:
        rng = np.random.default_rng(6000 * (seed + 1) + lo)
        # Disjoint value ranges guarantee a != b somewhere, so the
        # commutativity failure is deterministic, never seed-luck.
        return {
            "x": jnp.asarray(rng.integers(lo, hi, (1, n)).astype(np.int32))
        }

    return {
        "dense": d,
        "states": [st(1, 100), st(100, 200), st(200, 300)],
        "chain": None,
    }


# -- registration ------------------------------------------------------------

_BUILTIN_FIXTURES = {
    "topk": _fx_topk,
    "leaderboard": _fx_leaderboard,
    "wordcount": _fx_wordcount("wordcount"),
    "worddocumentcount": _fx_wordcount("worddocumentcount"),
    "average": _fx_average,
    "topk_rmv": _fx_topk_rmv,
}

for _name, _fx in _BUILTIN_FIXTURES.items():
    registry.register(_name, law_fixture=_fx)
del _name, _fx
