"""Blocking bridge client (the shape an erlport/gen_tcp client takes).

Failure semantics: a server-REPORTED error (the stream stays in sync)
raises `BridgeError` and the client remains usable. A TRANSPORT-class
failure — timeout, reset, corrupt frame, desynced request id — leaves
the reply stream unusable; with `retries=0` (the default) the client is
poisoned, exactly the pre-reconnect behavior. With `retries>0` the
client reconnects with capped exponential backoff and RESENDS the same
request under the idempotent `icall` form: a client-chosen random token
plus the request id lets the server dedup, so a request whose reply was
lost in the reset is not executed twice (grid_apply is not idempotent).

The `timeout` applies end to end: to the initial connect, to every recv
while waiting for a reply, and to every reconnect.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Any, List, Optional, Tuple

from ..core.etf import Atom
from ..obs import events as obs_events
from ..utils import faults
from ..utils.metrics import Metrics
from . import protocol as P


class BridgeError(RuntimeError):
    pass


class _ServerError(Exception):
    """Internal: an error the *server* reported over an in-sync stream —
    re-raised as BridgeError without poisoning the connection."""


class BridgeClient:
    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 30.0,
        retries: int = 0,
        backoff_base: float = 0.05,
        backoff_max: float = 1.0,
        metrics: Optional[Metrics] = None,
    ):
        self._host, self._port = host, port
        self._timeout = timeout
        self._retries = int(retries)
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self.metrics = metrics if metrics is not None else Metrics()
        # Client-incarnation token for idempotent resends (icall dedup key
        # on the server). Fresh per client object: a NEW client must not
        # collide with a previous incarnation's cached replies.
        self._token = os.urandom(8)
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()
        self._req = 0
        self._closed = False
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        self._buf = bytearray()

    def _drop_sock(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._buf = bytearray()

    def close(self) -> None:
        self._closed = True
        self._drop_sock()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def call(self, op: Any) -> Any:
        if self._closed:
            raise BridgeError("client is closed")
        self._req += 1
        req_id = self._req
        attempt = 0
        while True:
            try:
                if self._sock is None:
                    self._connect()
                return self._roundtrip(req_id, op)
            except _ServerError as e:
                raise BridgeError(str(e)) from None
            except Exception:
                # Transport-class failure: the reply stream is unusable.
                self._drop_sock()
                if attempt >= self._retries:
                    self._closed = True
                    raise
                attempt += 1
                self.metrics.count("bridge.reconnects")
                obs_events.emit(
                    "bridge.reconnect", req_id=req_id, attempt=attempt
                )
                time.sleep(
                    min(self._backoff_max,
                        self._backoff_base * (2.0 ** (attempt - 1)))
                )

    def _roundtrip(self, req_id: int, op: Any) -> Any:
        # icall (not call): resends after a reconnect must dedup on the
        # server — see module docstring.
        self._sock.sendall(P.pack_frame(P.icall(self._token, req_id, op)))
        while True:
            for term in P.unpack_frames(self._buf):
                rid, ok, payload = P.parse_reply(term)
                if rid != req_id:
                    raise BridgeError(f"reply for {rid}, expected {req_id}")
                if not ok:
                    raise _ServerError(P.error_text(payload))
                return payload
            if faults.ACTIVE:
                faults.fire("bridge.read")
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                raise BridgeError("connection closed")
            self._buf += chunk

    # -- scalar surface ----------------------------------------------------

    def new(self, type_name: str, *args: Any) -> Any:
        return self.call((Atom("new"), Atom(type_name), list(args)))

    def from_binary(self, type_name: str, blob: bytes) -> Any:
        return self.call((Atom("from_binary"), Atom(type_name), blob))

    def downstream(self, handle: Any, op: Tuple[str, Any], dc: Any, ts: int) -> Any:
        return self.call((Atom("downstream"), handle, P.op_to_term(op), dc, ts))

    def update(self, handle: Any, effect_term: Any) -> List[Any]:
        return self.call((Atom("update"), handle, effect_term))

    def value(self, handle: Any) -> Any:
        return self.call((Atom("value"), handle))

    def to_binary(self, handle: Any) -> bytes:
        return self.call((Atom("to_binary"), handle))

    def equal(self, h1: Any, h2: Any) -> bool:
        return self.call((Atom("equal"), h1, h2))

    def metrics_text(self) -> str:
        """Scrape the server's live registry in-band: OpenMetrics text
        over the data-plane connection (the {metrics} op)."""
        out = self.call((Atom("metrics"),))
        return bytes(out).decode("utf-8")

    def query(self, payload: bytes) -> bytes:
        """Serve-plane read over the data-plane connection: {query,
        Payload} -> canonical response bytes, byte-identical to the tcp
        query frame and POST /query for the same request."""
        return bytes(self.call((Atom("query"), bytes(payload))))

    def compact(self, handle: Any, effect_terms: List[Any]) -> List[Any]:
        return self.call((Atom("compact"), handle, effect_terms))

    def grid_compact(
        self, type_name: str, effect_terms: List[Any], m_keep: int = 0
    ) -> List[Any]:
        """Whole-log vectorized compaction of an effect-op log (no handle:
        stateless). m_keep=0 keeps every non-dominated add (reference
        compaction semantics); >0 bounds survivors per id."""
        params = [(Atom("m_keep"), m_keep)] if m_keep else []
        return self.call(
            (Atom("grid_compact"), Atom(type_name), params, effect_terms)
        )

    def free(self, handle: Any) -> None:
        self.call((Atom("free"), handle))

    # -- registry / per-type predicates ------------------------------------

    def is_type(self, type_name: str) -> bool:
        return self.call((Atom("is_type"), Atom(type_name)))

    def generates_extra_operations(self, type_name: str) -> bool:
        return self.call((Atom("generates_extra_operations"), Atom(type_name)))

    def is_operation(self, type_name: str, op: Tuple[str, Any]) -> bool:
        return self.call((Atom("is_operation"), Atom(type_name), P.op_to_term(op)))

    def require_state_downstream(self, type_name: str, op: Tuple[str, Any]) -> bool:
        return self.call(
            (Atom("require_state_downstream"), Atom(type_name), P.op_to_term(op))
        )

    def is_replicate_tagged(self, type_name: str, effect_term: Any) -> bool:
        return self.call(
            (Atom("is_replicate_tagged"), Atom(type_name), effect_term)
        )

    def batch_merge(self, type_name: str, items: List[Any]) -> Any:
        """Join N states (handles and/or `to_binary` blobs) in one batched
        device pass on the worker; returns a new handle to the merged
        state — the north-star `batch_merge` entry point. For the MONOID
        types (average, wordcounts) the inputs' op histories must be
        disjoint (+ is not idempotent — see core.batch_merge); the JOIN
        types tolerate arbitrary overlap."""
        return self.call((Atom("batch_merge"), Atom(type_name), list(items)))

    # -- dense grid surface ------------------------------------------------

    def grid_new(self, name: str, type_name: str = "topk_rmv", **params: int) -> None:
        """Create a dense grid of any registered type (topk_rmv, topk,
        leaderboard, average, wordcount, worddocumentcount); `params` are
        the type's geometry keys (see server._GRID_GEOMETRY)."""
        self.call(
            (
                Atom("grid_new"),
                name.encode(),
                Atom(type_name),
                {Atom(k): v for k, v in params.items()},
            )
        )

    def grid_apply(self, name: str, per_replica_ops: List[List[Any]]) -> int:
        return self.call((Atom("grid_apply"), name.encode(), per_replica_ops))

    def grid_apply_extras(self, name: str, per_replica_ops: List[List[Any]]):
        """Like grid_apply, but returns the generated extra effect ops
        per replica, in the grid's own op shapes so they feed straight
        back into grid_apply: topk_rmv yields dominated-add re-broadcast
        rmvs and rmv-driven promotion adds; leaderboard yields
        ban-promotion adds; the other types []."""
        return self.call(
            (Atom("grid_apply_extras"), name.encode(), per_replica_ops)
        )

    def grid_apply_packed(self, name: str, groups) -> int:
        """The packed-columns throughput surface (server._PACKED_COLUMNS):
        `groups` is a list of (tag, per_replica_counts, [column, ...])
        with numpy/sequence int data; each column carries that field for
        every op, concatenated in replica order, and ships as ONE i32-LE
        binary instead of per-op ETF tuples."""
        return self.call(
            (Atom("grid_apply_packed"), name.encode(), _pack_groups(groups))
        )

    def grid_apply_packed_multi(self, name: str, batches) -> int:
        """Multi-batch `grid_apply_packed` in ONE wire call. For topk_rmv
        the server validates every batch up front (all-or-nothing), then
        runs the sequential rounds as a single scan-fused device dispatch
        with one dominated-count readback — wire round-trip, upload,
        dispatch, and sync all amortize over len(batches). Other types
        apply batch by batch, amortizing the wire round-trip. Returns
        the total extras count (topk_rmv dominated elements)."""
        return self.call(
            (Atom("grid_apply_packed_multi"), name.encode(),
             [_pack_groups(groups) for groups in batches])
        )

    def grid_apply_extras_packed(self, name: str, groups):
        """Packed `grid_apply_extras`: same input form as
        grid_apply_packed; the generated extras come back as packed
        groups in the grid's own packed column orders (decoded here to
        (tag, counts, [columns]) numpy tuples), so they feed straight
        back into grid_apply_packed."""
        import numpy as np

        reply = self.call(
            (Atom("grid_apply_extras_packed"), name.encode(),
             _pack_groups(groups))
        )
        return [
            (
                str(tag),
                np.frombuffer(counts_bin, dtype="<i4"),
                [np.frombuffer(cb, dtype="<i4") for cb in col_bins],
            )
            for tag, counts_bin, col_bins in reply
        ]

    def grid_merge_all(self, name: str) -> None:
        self.call((Atom("grid_merge_all"), name.encode()))

    def grid_observe(self, name: str, replica: int = 0, key: int = 0):
        return self.call((Atom("grid_observe"), name.encode(), replica, key))

    def grid_to_binary(self, name: str) -> bytes:
        """Self-contained (geometry + state) snapshot of a dense grid."""
        return self.call((Atom("grid_to_binary"), name.encode()))

    def grid_from_binary(self, name: str, blob: bytes) -> None:
        """Rebuild a grid (geometry included in the blob) — the worker
        restart / site-clone path."""
        self.call((Atom("grid_from_binary"), name.encode(), blob))


def add(key: int, id_: Any, score: int, dc: int, ts: int):
    """Grid add op term."""
    return (Atom("add"), key, id_, score, dc, ts)


def rmv(key: int, id_: Any, vc: dict):
    """Grid removal op term; vc maps dc -> ts."""
    return (Atom("rmv"), key, id_, [(d, t) for d, t in sorted(vc.items())])

def _pack_i32_col(x) -> bytes:
    """One packed wire column: i32-LE bytes, loud on out-of-range values
    (a silent astype would truncate 2**40+7 to 7 and corrupt CRDT state
    undetectably; the tuple wire's ETF encoder raises on such ints too)."""
    import numpy as np

    arr = np.asarray(x)
    if arr.size and not np.issubdtype(arr.dtype, np.integer):
        # A float column (e.g. 3.7) would pass the range check below and
        # astype would silently truncate it to 3; the tuple wire's ETF
        # encoder rejects non-integers, so the packed wire must too.
        raise ValueError(f"packed column requires integer dtype, got {arr.dtype}")
    if arr.size and (int(arr.min()) < -(2**31) or int(arr.max()) >= 2**31):
        raise ValueError("packed column value out of i32 range")
    return arr.astype("<i4").tobytes()


def _pack_groups(groups):
    """Pack (tag, counts, [cols]) groups to the wire form — the Python
    twin of the Erlang client's pack_groups/1."""
    return [
        (Atom(tag), _pack_i32_col(counts), [_pack_i32_col(c) for c in cols])
        for tag, counts, cols in groups
    ]
