%% Erlang-side client for the antidote_ccrdt_tpu bridge worker.
%%
%% Routes the reference behaviour's invocation surface (the 12 callbacks an
%% Antidote host drives, upstream src/antidote_ccrdt.erl:47-59) over a
%% {packet,4} + External-Term-Format TCP connection to the persistent TPU
%% worker (antidote_ccrdt_tpu/bridge/server.py). An Antidote node loads
%% this module and calls through it exactly where it would call a local
%% data-type module; the CRDT states live in the worker, addressed by
%% integer handles, interchangeable with reference term_to_binary
%% snapshots via to_binary/from_binary.
%%
%% Wire protocol (bridge/protocol.py is the source of truth):
%%   frame   := u32_be length ++ term_to_binary(Term)   %% = {packet, 4}
%%   request := {call, ReqId, Op}
%%   reply   := {reply, ReqId, {ok, Result} | {error, Binary}}
%%
%% Every request this module sends is term_to_binary of a plain tuple, so
%% the byte stream is pinned — tests/test_bridge_erl.py vendors the exact
%% frames these functions produce and asserts bridge/protocol.py decodes
%% them (and, where the encoding is canonical, produces identical bytes).
%%
%% Also runnable as a smoke-test escript against a live worker:
%%     escript antidote_ccrdt_tpu.erl [Host [Port]]
%% (tests/test_bridge_erl.py runs this automatically when escript is on
%% PATH.)

-module(antidote_ccrdt_tpu).

-export([connect/2, close/1, call/2,
         new/2, new/3, from_binary/3, downstream/5, update/3, value/2,
         to_binary/2, equal/3, compact/3, free/2, batch_merge/3,
         is_type/2, generates_extra_operations/2, is_operation/3,
         require_state_downstream/3, is_replicate_tagged/3,
         grid_new/4, grid_apply/3, grid_apply_extras/3,
         grid_apply_packed/3, grid_apply_extras_packed/3,
         grid_apply_packed_multi/3, pack_i32/1,
         grid_merge_all/2, grid_observe/4,
         grid_to_binary/2, grid_from_binary/3,
         wire_atoms/0, main/1]).

-define(TIMEOUT, 30000).

%% The protocol atoms plus every effect tag the data types emit — for
%% reference, and so they are interned at module load. Replies are decoded
%% with plain binary_to_term/1, NOT [safe]: the worker holds all CRDT
%% state and sits inside the deployment's trust boundary, and replies can
%% legitimately carry atoms this VM has never seen (DC ids from foreign
%% reference snapshots loaded via from_binary/3), which [safe] would
%% reject with badarg.
wire_atoms() ->
    [reply, ok, error, nil, true, false, call,
     add, add_r, rmv, rmv_r, add_map, add_counts, ban, ban_r, noop].

connect(Host, Port) ->
    gen_tcp:connect(Host, Port,
                    [binary, {packet, 4}, {active, false}], ?TIMEOUT).

close(Sock) ->
    gen_tcp:close(Sock).

%% One request/reply round trip. Request ids are VM-unique so concurrent
%% processes may share a connection only with external serialization; one
%% connection per caller is the intended shape (the worker is threaded).
call(Sock, Op) ->
    ReqId = erlang:unique_integer([positive, monotonic]),
    ok = gen_tcp:send(Sock, term_to_binary({call, ReqId, Op})),
    {ok, Bin} = gen_tcp:recv(Sock, 0, ?TIMEOUT),
    case binary_to_term(Bin) of
        {reply, ReqId, {ok, Result}} -> {ok, Result};
        {reply, ReqId, {error, Msg}} -> {error, Msg};
        Other -> {error, {bad_reply, Other}}
    end.

%% -- the callback surface (antidote_ccrdt.erl:47-59 over the wire) -------

new(Sock, Type) ->
    new(Sock, Type, []).

new(Sock, Type, Args) when is_atom(Type), is_list(Args) ->
    call(Sock, {new, Type, Args}).

from_binary(Sock, Type, Bin) when is_atom(Type), is_binary(Bin) ->
    call(Sock, {from_binary, Type, Bin}).

%% DcId/Ts replace the reference's ?DC_META_DATA/?TIME shims: the host
%% passes its identity and clock explicitly (the library's only
%% nondeterminism made an argument — see core/clock.py).
downstream(Sock, Handle, Op, DcId, Ts) ->
    call(Sock, {downstream, Handle, Op, DcId, Ts}).

update(Sock, Handle, Effect) ->
    call(Sock, {update, Handle, Effect}).

value(Sock, Handle) ->
    call(Sock, {value, Handle}).

to_binary(Sock, Handle) ->
    call(Sock, {to_binary, Handle}).

equal(Sock, H1, H2) ->
    call(Sock, {equal, H1, H2}).

compact(Sock, Handle, Effects) when is_list(Effects) ->
    call(Sock, {compact, Handle, Effects}).

free(Sock, Handle) ->
    call(Sock, {free, Handle}).

%% The north-star entry point: join N states (handles or reference
%% binaries) in one batched device pass; returns a new handle.
batch_merge(Sock, Type, Items) when is_atom(Type), is_list(Items) ->
    call(Sock, {batch_merge, Type, Items}).

%% -- registry / per-type predicates (antidote_ccrdt.erl:61-65) -----------

is_type(Sock, Type) ->
    call(Sock, {is_type, Type}).

generates_extra_operations(Sock, Type) ->
    call(Sock, {generates_extra_operations, Type}).

is_operation(Sock, Type, Op) ->
    call(Sock, {is_operation, Type, Op}).

require_state_downstream(Sock, Type, Op) ->
    call(Sock, {require_state_downstream, Type, Op}).

is_replicate_tagged(Sock, Type, Effect) ->
    call(Sock, {is_replicate_tagged, Type, Effect}).

%% -- dense grids (the TPU batch surface) ---------------------------------

%% Params is a map, e.g. #{n_replicas => 2, n_keys => 1, n_ids => 1024,
%% n_dcs => 2, size => 100, slots_per_id => 4}.
grid_new(Sock, Grid, Type, Params) when is_map(Params) ->
    call(Sock, {grid_new, Grid, Type, Params}).

%% OpsPerReplica: one op list per replica row. Op shapes per grid type:
%%   topk_rmv     {add, Key, Id, Score, Dc, Ts} | {rmv, Key, Id, [{Dc, Ts}]}
%%   topk         {add, Key, Id, Score}
%%   leaderboard  {add, Key, Id, Score} | {ban, Key, Id}
%%   average      {add, Key, Value, Count}
%%   wordcount / worddocumentcount  {add, Key, TokenId}
%%   worddocumentcount also accepts raw per-token records
%%     {doc_add, Key, DocId, UniqId, TokenId}  (whole batch must be
%%     doc_add; per-document dedup then runs on device — UniqId is the
%%     string-identity id, one document's records must stay in one batch)
grid_apply(Sock, Grid, OpsPerReplica) when is_list(OpsPerReplica) ->
    call(Sock, {grid_apply, Grid, OpsPerReplica}).

%% Like grid_apply/3 but returns the generated extra effect ops per
%% replica row (update/2 extras over the grid wire), in the grid's OWN
%% op shapes so they feed straight back into grid_apply: topk_rmv yields
%% dominated-add re-broadcast {rmv, Key, Id, [{Dc,Ts}]} and rmv-driven
%% promotions {add, Key, Id, Score, Dc, Ts}; leaderboard yields
%% ban-promotions {add, Key, Id, Score}; other types [].
grid_apply_extras(Sock, Grid, OpsPerReplica) when is_list(OpsPerReplica) ->
    call(Sock, {grid_apply_extras, Grid, OpsPerReplica}).

%% Packed-columns throughput surface: Groups is a list of
%% {Tag, Counts, Cols} where Counts is one op count per replica row and
%% each Col carries that field's value for EVERY op, concatenated in
%% replica order (column order per tag matches grid_apply's tuple field
%% order; topk_rmv rmv columns are key, id, vc_len, vc_dc, vc_ts with
%% the vc entries concatenated). Integer lists are packed here into one
%% i32-little binary per column — a single binary comprehension instead
%% of per-op ETF tuples, which is what lets a BEAM host feed the device
%% at wire speed. Pre-packed binaries pass through unchanged.
grid_apply_packed(Sock, Grid, Groups) when is_list(Groups) ->
    call(Sock, {grid_apply_packed, Grid, pack_groups(Groups)}).

%% Multi-batch packed apply: several packed batches in ONE wire call.
%% For topk_rmv the server validates every batch up front
%% (all-or-nothing) and runs the sequential rounds as a single
%% scan-fused device dispatch with one extras readback, so the wire
%% round-trip, upload, dispatch and sync all amortize over
%% length(Batches); other types apply batch by batch (wire round-trip
%% amortized). Returns the total extras count.
grid_apply_packed_multi(Sock, Grid, Batches) when is_list(Batches) ->
    call(Sock, {grid_apply_packed_multi, Grid,
                [pack_groups(Groups) || Groups <- Batches]}).

%% Packed apply_extras: the reply is the generated extras as packed
%% groups in this grid's own packed column orders ({Tag, CountsBin,
%% [ColBin...]} with i32-little binaries) — feed them straight back into
%% grid_apply_packed, or unpack with [X || <<X:32/little-signed>> <= Bin].
grid_apply_extras_packed(Sock, Grid, Groups) when is_list(Groups) ->
    call(Sock, {grid_apply_extras_packed, Grid, pack_groups(Groups)}).

pack_groups(Groups) ->
    [{Tag, pack_i32(Counts), [pack_i32(C) || C <- Cols]}
     || {Tag, Counts, Cols} <- Groups].

pack_i32(Bin) when is_binary(Bin) -> Bin;
pack_i32(Ints) when is_list(Ints) ->
    %% check_i32 makes an out-of-range value a function_clause error —
    %% a bare <<X:32>> would truncate silently and corrupt CRDT state.
    << <<(check_i32(X)):32/little-signed>> || X <- Ints >>.

check_i32(X) when is_integer(X), X >= -2147483648, X =< 2147483647 -> X.

grid_merge_all(Sock, Grid) ->
    call(Sock, {grid_merge_all, Grid}).

grid_observe(Sock, Grid, Replica, Key) ->
    call(Sock, {grid_observe, Grid, Replica, Key}).

%% Self-contained snapshot (geometry + state); grid_from_binary/3 rebuilds
%% the grid on a restarted worker or a clone site.
grid_to_binary(Sock, Grid) ->
    call(Sock, {grid_to_binary, Grid}).

grid_from_binary(Sock, Grid, Bin) when is_binary(Bin) ->
    call(Sock, {grid_from_binary, Grid, Bin}).

%% -- escript smoke test ---------------------------------------------------

main(Args) ->
    Host = case Args of [H | _] -> H; _ -> "127.0.0.1" end,
    Port = case Args of [_, P | _] -> list_to_integer(P); _ -> 7077 end,
    {ok, S} = connect(Host, Port),
    {ok, true} = is_type(S, average),
    {ok, false} = is_type(S, not_a_type),
    {ok, true} = generates_extra_operations(S, topk_rmv),

    %% scalar surface: average end to end
    {ok, H} = new(S, average),
    {ok, Eff} = downstream(S, H, {add, 5}, {replica1, 0}, 1),
    {ok, []} = update(S, H, Eff),
    {ok, Eff2} = downstream(S, H, {add, {15, 2}}, {replica1, 0}, 2),
    {ok, []} = update(S, H, Eff2),
    {ok, V} = value(S, H),
    io:format("average value: ~p~n", [V]),

    %% snapshot round trip + batched join
    {ok, Bin} = to_binary(S, H),
    {ok, H2} = from_binary(S, average, Bin),
    {ok, true} = equal(S, H, H2),
    {ok, H3} = batch_merge(S, average, [H, Bin]),
    {ok, V3} = value(S, H3),
    io:format("batch_merge value: ~p~n", [V3]),

    %% topk_rmv with an extra-op re-broadcast (reference :234-237)
    {ok, T} = new(S, topk_rmv, [2]),
    {ok, AddEff} = downstream(S, T, {add, {1, 42}}, {dc1, 0}, 1),
    {ok, []} = update(S, T, AddEff),
    {ok, RmvEff} = downstream(S, T, {rmv, 1}, {dc1, 0}, 2),
    {ok, _} = update(S, T, RmvEff),
    {ok, Extras} = update(S, T, AddEff),  %% re-deliver dominated add
    true = Extras =/= [],
    io:format("topk_rmv re-broadcast extras: ~p~n", [Extras]),

    {ok, true} = free(S, H3),

    %% dense grids beyond the flagship: a MONOID grid (average) and a
    %% JOIN grid (leaderboard) batched over the same surface
    {ok, true} = grid_new(S, ga, average, #{n_replicas => 2, n_keys => 1}),
    {ok, 0} = grid_apply(S, ga, [[{add, 0, 10, 1}], [{add, 0, 20, 1}]]),
    {ok, true} = grid_merge_all(S, ga),
    {ok, {30, 2}} = grid_observe(S, ga, 0, 0),
    {ok, true} = grid_new(S, gl, leaderboard,
                          #{n_replicas => 2, n_players => 8, size => 2}),
    {ok, 0} = grid_apply(S, gl, [[{add, 0, 1, 10}],
                                 [{ban, 0, 1}, {add, 0, 2, 5}]]),
    {ok, true} = grid_merge_all(S, gl),
    {ok, [{2, 5}]} = grid_observe(S, gl, 0, 0),
    io:format("dense grids (average + leaderboard) OK~n", []),

    %% extras over the grid wire: a ban that opens a slot re-broadcasts
    %% the promoted player in the grid's own add shape — feed it back
    {ok, true} = grid_new(S, gp, leaderboard,
                          #{n_replicas => 1, n_players => 8, size => 1}),
    {ok, [[]]} = grid_apply_extras(S, gp, [[{add, 0, 1, 9}, {add, 0, 2, 4}]]),
    {ok, [[{add, 0, 2, 4}]]} = grid_apply_extras(S, gp, [[{ban, 0, 1}]]),
    {ok, 0} = grid_apply(S, gp, [[{add, 0, 2, 4}]]),

    %% device-side per-document dedup over the wire
    {ok, true} = grid_new(S, gd, worddocumentcount,
                          #{n_replicas => 1, n_buckets => 8}),
    {ok, 0} = grid_apply(S, gd, [[{doc_add, 0, 0, 5, 3},
                                  {doc_add, 0, 0, 5, 3},
                                  {doc_add, 0, 1, 5, 3}]]),
    {ok, [{3, 2}]} = grid_observe(S, gd, 0, 0),
    io:format("grid extras + doc dedup OK~n", []),

    ok = close(S),
    io:format("bridge smoke OK~n", []),
    halt(0).
