"""Bridge wire protocol: Erlang `{packet, 4}` framing + ETF terms.

The north-star integration path (SURVEY.md §5 "Distributed communication
backend") is a bridge feeding op batches from a BEAM-shaped host into the
persistent JAX worker. The protocol is what an Erlang port/socket client
speaks natively:

    frame   := u32_be length ++ payload
    payload := term_to_binary(Request | Reply)

Requests are tagged tuples `{call, ReqId, Op}` or `{icall, Token, ReqId,
Op}`; replies are `{reply, ReqId, {ok, Result} | {error, Binary |
{Kind, Binary}}}`. ReqIds let a client pipeline requests.

`icall` is the IDEMPOTENT request form: `Token` is a client-chosen
random binary identifying the client incarnation, and the server keeps
a bounded (Token, ReqId) -> Reply cache, so a request RESENT after a
reconnect (the client cannot know whether the first send executed)
returns the original reply instead of executing twice — required for
non-idempotent ops like grid_apply. `call` stays for one-shot clients
and BEAM hosts that manage their own retries.

Error replies carry `{Kind, Message}` where Kind is an atom naming the
exception class (`badarg`-style structured errors a host can switch
on); the bare-binary form remains accepted on decode for old peers. Op shapes (atoms abbreviated as Python `Atom`):

    {new, Type, Args}                 -> {ok, Handle}      scalar instance
    {from_binary, Type, Bin}          -> {ok, Handle}      load BEAM snapshot
    {downstream, Handle, Op, Dc, Ts}  -> {ok, Effect | nil}
    {update, Handle, Effect}          -> {ok, [ExtraOps]}
    {value, Handle}                   -> {ok, Value}
    {to_binary, Handle}               -> {ok, Bin}         reference format
    {equal, H1, H2}                   -> {ok, Bool}
    {compact, Handle, [Effect]}       -> {ok, [Effect]}    whole-log compaction
    {free, Handle}                    -> {ok, true}
    {batch_merge, Type, [H | Bin]}    -> {ok, Handle}      join N states, one pass
    {is_type, Type}                   -> {ok, Bool}        registry predicates
    {generates_extra_operations, Type}-> {ok, Bool}
    {is_operation, Type, Op}          -> {ok, Bool}        per-type predicates
    {require_state_downstream, Type, Op} -> {ok, Bool}
    {is_replicate_tagged, Type, Effect} -> {ok, Bool}
    {grid_new, Grid, Type, Params}    -> {ok, true}        dense grid (TPU)
    {grid_apply, Grid, OpsPerReplica} -> {ok, NDominated}
    {grid_merge_all, Grid}            -> {ok, true}        fold replicas (join)
    {grid_observe, Grid, Replica, Key}-> {ok, [{Id, Score}]}

Handles and grid names are arbitrary terms chosen by the server/client.
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Tuple

from ..core import etf
from ..core.etf import Atom

A_CALL = Atom("call")
A_ICALL = Atom("icall")
A_REPLY = Atom("reply")
A_OK = Atom("ok")
A_ERROR = Atom("error")
A_NIL = Atom("nil")

MAX_FRAME = 256 * 1024 * 1024


def pack_frame(term: Any) -> bytes:
    payload = etf.encode(term)
    return struct.pack(">I", len(payload)) + payload


def unpack_frames(buf: bytearray):
    """Yield decoded terms from `buf`, consuming complete frames in place."""
    while True:
        if len(buf) < 4:
            return
        (n,) = struct.unpack(">I", bytes(buf[:4]))
        if n > MAX_FRAME:
            raise ValueError(f"frame of {n} bytes exceeds limit")
        if len(buf) < 4 + n:
            return
        payload = bytes(buf[4 : 4 + n])
        del buf[: 4 + n]
        yield etf.decode(payload)


def call(req_id: int, op: Any) -> Any:
    return (A_CALL, req_id, op)


def icall(token: bytes, req_id: int, op: Any) -> Any:
    """Idempotent request: the server dedups on (token, req_id)."""
    return (A_ICALL, token, req_id, op)


def reply_ok(req_id: int, result: Any) -> Any:
    return (A_REPLY, req_id, (A_OK, result))


def reply_error(req_id: Any, message: str, kind: str = "error") -> Any:
    """Structured error frame: {error, {Kind, Message}}. Kind is an atom
    (typically the exception class name) a host can dispatch on without
    parsing the human-readable message."""
    return (A_REPLY, req_id, (A_ERROR, (Atom(kind), message.encode("utf-8"))))


def error_text(payload: Any) -> str:
    """Render an error payload — structured {Kind, Msg} or legacy bare
    binary — as the "Kind: message" string clients raise."""
    if (
        isinstance(payload, tuple)
        and len(payload) == 2
        and isinstance(payload[0], Atom)
    ):
        kind, msg = payload
        if isinstance(msg, bytes):
            msg = msg.decode("utf-8", "replace")
        return f"{kind}: {msg}"
    if isinstance(payload, bytes):
        return payload.decode("utf-8", "replace")
    return repr(payload)


# --- term <-> op conversion (shared by server and client) -----------------


def term_to_py(x: Any) -> Any:
    """Wire term -> python payload: atoms stay Atom, utf-8 binaries become
    str (non-utf-8 stay bytes), containers recurse."""
    if isinstance(x, bytes):
        try:
            return x.decode("utf-8")
        except UnicodeDecodeError:
            return x
    if isinstance(x, tuple):
        return tuple(term_to_py(e) for e in x)
    if isinstance(x, list):
        return [term_to_py(e) for e in x]
    if isinstance(x, dict):
        return {term_to_py(k): term_to_py(v) for k, v in x.items()}
    return x


def py_to_term(x: Any) -> Any:
    if isinstance(x, str) and not isinstance(x, Atom):
        return x.encode("utf-8")
    if isinstance(x, tuple):
        return tuple(py_to_term(e) for e in x)
    if isinstance(x, (list, frozenset, set)):
        return [py_to_term(e) for e in x]
    if isinstance(x, dict):
        return {py_to_term(k): py_to_term(v) for k, v in x.items()}
    return x


def op_from_term(t: Any) -> Tuple[str, Any]:
    """{add, Payload} -> ("add", payload)."""
    if not (isinstance(t, tuple) and len(t) == 2 and isinstance(t[0], Atom)):
        raise ValueError(f"bad op term: {t!r}")
    return (str(t[0]), term_to_py(t[1]))


def op_to_term(op: Optional[Tuple[str, Any]]) -> Any:
    if op is None:
        return A_NIL
    return (Atom(op[0]), py_to_term(op[1]))


def parse_reply(term: Any) -> Tuple[int, bool, Any]:
    """-> (req_id, ok, result_or_error_message)"""
    if not (isinstance(term, tuple) and len(term) == 3 and term[0] == A_REPLY):
        raise ValueError(f"not a reply term: {term!r}")
    _, req_id, body = term
    tag, payload = body
    if tag == A_OK:
        return req_id, True, payload
    return req_id, False, payload
