from .client import BridgeClient  # noqa: F401
from .server import BridgeServer  # noqa: F401
