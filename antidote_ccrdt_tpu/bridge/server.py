"""Bridge server: a persistent CCRDT worker a BEAM-shaped host can drive.

Stands in for the reference's host integration surface (the Antidote side
of the behaviour contract, SURVEY.md §1): a threaded TCP server speaking
`{packet, 4}` + ETF (see `protocol`), holding

* **scalar instances** — handle -> (type, state); the full callback
  surface (downstream/update/value/compact/to_binary/...) over the wire,
  states interchangeable with reference `term_to_binary` snapshots; and
* **dense grids** — named [n_replicas, n_keys] dense states on the JAX
  backend (TPU when available); op batches are packed to the dense op
  structs, applied in one dispatch, and replicas fold with the lattice
  merge — the north-star `batch_merge` exposed to a host.

Concurrency: one OS thread per connection, per-OBJECT locking (round-2;
round 1 had one global lock, so a ~60ms dense grid dispatch stalled every
other client):

* every scalar handle and every grid has its own lock, created lazily;
* ops touching several handles (equal, batch_merge) acquire their locks
  in sorted order (no deadlock);
* a short meta lock guards only the handle/grid maps, lock tables and id
  allocation, and is never held while waiting on an object lock;
* registry predicates are pure reads and run lock-free.

Scalar states are copy-on-write (every `update` builds a new value), so
holding an object lock only for the duration of the op keeps readers of
old state references safe. A long grid dispatch therefore blocks ONLY
callers of that same grid — pinned by
`tests/test_bridge.py::test_long_grid_op_does_not_block_scalar_ops`.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core import wire
from ..core.behaviour import registry
from ..core.etf import Atom
from ..obs import events as obs_events
from ..utils.metrics import Metrics
from . import protocol as P


# Term <-> op conversion lives in protocol.py (shared with the client).
from .protocol import op_from_term, op_to_term, py_to_term, term_to_py

_from_term = term_to_py
_to_term = py_to_term


# --- dense grids ----------------------------------------------------------


# Geometry schema per dense type: (key, default) pairs beyond the shared
# n_replicas/n_keys; a callable default is resolved against the grid
# (the reference host's per-type parameters, antidote_ccrdt.erl:47-59 —
# every registered type gets the batch surface, not just the flagship).
_GRID_GEOMETRY: Dict[str, Tuple[Tuple[str, Any], ...]] = {
    "topk_rmv": (  # frozen wire format — golden bytes pin it
        ("n_ids", 1024), ("n_dcs", lambda g: g.R),
        ("size", 100), ("slots_per_id", 4),
    ),
    "topk": (("n_ids", 1024), ("size", 100)),
    "leaderboard": (("n_players", 1024), ("size", 100)),
    "average": (),
    "wordcount": (("n_buckets", 1024),),
    "worddocumentcount": (("n_buckets", 1024),),
}

# Packed-columns batch surface (round 4): column order per (type, tag).
# Each column ships as ONE ETF binary of little-endian i32 — the values
# of that field for every op, concatenated in replica order — plus a
# per-replica op-count binary. This replaces per-op ETF tuples on the
# throughput path: the term surface spent most of each grid call
# decoding/looping millions of small tuples in Python, while the packed
# surface is np.frombuffer + vectorized checks (and a BEAM client builds
# the binaries with one binary comprehension per column).
# topk_rmv's rmv carries a ragged vc list per op: vc_len gives each op's
# entry count and vc_dc/vc_ts hold the concatenated entries (their
# length is sum(vc_len), not sum(counts)).
_PACKED_COLUMNS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    ("average", "add"): ("key", "value", "count"),
    ("topk", "add"): ("key", "id", "score"),
    ("topk_rmv", "add"): ("key", "id", "score", "dc", "ts"),
    ("topk_rmv", "rmv"): ("key", "id", "vc_len", "vc_dc", "vc_ts"),
    ("leaderboard", "add"): ("key", "id", "score"),
    ("leaderboard", "ban"): ("key", "id"),
    ("wordcount", "add"): ("key", "token"),
    ("worddocumentcount", "add"): ("key", "token"),
    ("worddocumentcount", "doc_add"): ("key", "doc", "uniq", "token"),
}


# Padding fills per op-plane for the scan-fused multi path, by scan kind.
# Every fill is the same semantically-inert sentinel the per-batch padding
# (_pad_cols / valid planes) already uses: ts=0 / valid=False / token=-1 /
# rmv_id=-1 ops are dropped by the engines.
_MULTI_FILLS = {
    "topk_rmv": (0, 0, 0, 0, 0, 0, -1, 0),
    "topk_rmv_packed_ids": (0, 0, 0, -1, 0),
    "average": (0, 0, 0),
    "topk": (0, 0, 0, False),
    "leaderboard": (0, 0, 0, False, 0, 0, False),
    "wordcount": (0, -1),
    "worddoc_doc": (0, 0, 0, -1),
}

# The scan path's host->device upload is the multi surface's measured
# binding constraint (BENCHALL_r05 decomposition), so when the geometry
# fits, (key, id, dc) pack losslessly into ONE i32 per add — 5 planes ->
# 3 — and (key, id) per rmv — 2 -> 1 (the on-device unpack is a pair of
# fused divmods). Tests force the unpacked fallback by patching this
# limit down.
_PACKED_IDS_LIMIT = 2**31

_SCAN_FNS: Dict[str, Any] = {}


def _get_scan_fn(kind: str):
    """Jitted (dense-static) scan over stacked op batches: the sequential
    multi-batch apply as ONE device dispatch, per scan kind. Built lazily
    so importing the bridge never initializes a JAX backend (multihost
    import rule); jax.jit's shape keying caches one executable per
    (MB, widths) bucket."""
    if kind in _SCAN_FNS:
        return _SCAN_FNS[kind]
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    if kind == "topk_rmv":
        from ..models.topk_rmv_dense import TopkRmvOps

        def step(dense, st, a):
            st, ex = dense.apply_ops(st, TopkRmvOps(
                add_key=a[0], add_id=a[1], add_score=a[2], add_dc=a[3],
                add_ts=a[4], rmv_key=a[5], rmv_id=a[6], rmv_vc=a[7],
            ))
            return st, jnp.sum(ex.dominated)
    elif kind == "topk_rmv_packed_ids":
        from ..models.topk_rmv_dense import TopkRmvOps

        def step(dense, st, a):
            # a = (add_kid_dc, add_score, add_ts, rmv_kid, rmv_vc):
            # kid_dc = (key*I + id)*D + dc; rmv kid = key*I + id with -1
            # marking padding (kept out of the packed domain so the
            # engine's rmv_id < 0 drop fires exactly as unpacked).
            I, D = dense.I, dense.D
            kid = a[0] // D
            rk = a[3]
            pad = rk < 0
            st, ex = dense.apply_ops(st, TopkRmvOps(
                add_key=kid // I, add_id=kid % I, add_score=a[1],
                add_dc=a[0] % D, add_ts=a[2],
                rmv_key=jnp.where(pad, 0, rk // I),
                rmv_id=jnp.where(pad, -1, rk % I),
                rmv_vc=a[4],
            ))
            return st, jnp.sum(ex.dominated)
    elif kind == "average":
        from ..models.average import AverageOps

        def step(dense, st, a):
            st, _ = dense.apply_ops(
                st, AverageOps(key=a[0], value=a[1], count=a[2])
            )
            return st, jnp.int32(0)
    elif kind == "topk":
        from ..models.topk import TopkOps

        def step(dense, st, a):
            st, _ = dense.apply_ops(
                st, TopkOps(key=a[0], id=a[1], score=a[2], valid=a[3])
            )
            return st, jnp.int32(0)
    elif kind == "leaderboard":
        from ..models.leaderboard import LeaderboardOps

        def step(dense, st, a):
            st, _ = dense.apply_ops(st, LeaderboardOps(
                add_key=a[0], add_id=a[1], add_score=a[2], add_valid=a[3],
                ban_key=a[4], ban_id=a[5], ban_valid=a[6],
            ))
            return st, jnp.int32(0)
    elif kind == "wordcount":
        from ..models.wordcount import WordcountOps

        def step(dense, st, a):
            st, _ = dense.apply_ops(st, WordcountOps(key=a[0], token=a[1]))
            return st, jnp.int32(0)
    elif kind == "worddoc_doc":
        from ..models.wordcount import WordDocOps

        def step(dense, st, a):
            st, _ = dense.apply_doc_ops(
                st, WordDocOps(key=a[0], doc=a[1], uniq=a[2], token=a[3])
            )
            return st, jnp.int32(0)
    else:  # pragma: no cover - registry and kinds move together
        raise ValueError(f"no scan kind {kind!r}")

    @functools.partial(jax.jit, static_argnums=0)
    def scan_apply(dense, state, stacked):
        state, counts = lax.scan(
            lambda st, arrs: step(dense, st, arrs), state, stacked
        )
        return state, jnp.sum(counts)

    _SCAN_FNS[kind] = scan_apply
    return scan_apply


def _i32_col(buf, what: str) -> np.ndarray:
    if not isinstance(buf, (bytes, bytearray, memoryview)):
        raise ValueError(f"packed {what} must be a binary")
    if len(buf) % 4:
        raise ValueError(f"packed {what} length {len(buf)} not a multiple of 4")
    # copy=False: zero-copy on little-endian hosts (the hot path); only a
    # big-endian host pays the byte-order normalization copy.
    return np.frombuffer(buf, dtype="<i4").astype(np.int32, copy=False)


def _bin_col(arr) -> bytes:
    """Pack an int array as one i32-LE reply column."""
    return np.ascontiguousarray(arr, dtype="<i4").tobytes()


def _reject(mask: np.ndarray, values: np.ndarray, msg: str) -> None:
    """Loud wire validation, vectorized: report the first offender with
    the same wording the per-op tuple packers use."""
    if mask.any():
        raise ValueError(msg.format(int(values[np.argmax(mask)])))


class _Grid:
    """A named dense CRDT grid on the JAX backend — any registered dense
    type; op packing and observe shape dispatch per type below."""

    def __init__(self, type_name: str, params: Dict[Any, Any]):
        def geti(key, default):
            return int(params.get(Atom(key), default))

        if type_name not in _GRID_GEOMETRY:
            raise ValueError(
                f"dense grids support {sorted(_GRID_GEOMETRY)}; "
                f"got {type_name!r}"
            )
        self.type_name = type_name
        self.R = geti("n_replicas", 2)
        self.NK = geti("n_keys", 1)
        # Resolved geometry (defaults applied) — embedded in snapshots so
        # grid_from_binary is self-contained.
        self.geometry = {"n_replicas": self.R, "n_keys": self.NK}
        for key, default in _GRID_GEOMETRY[type_name]:
            self.geometry[key] = geti(
                key, default(self) if callable(default) else default
            )
        # Constructed through the registry's dense-factory surface — the
        # same path any embedder uses.
        dense_kwargs = {
            k: v for k, v in self.geometry.items()
            if k not in ("n_replicas", "n_keys")
        }
        self.dense = registry.make_dense(type_name, **dense_kwargs)
        self.state = self.dense.init(n_replicas=self.R, n_keys=self.NK)

    def to_binary(self) -> bytes:
        """Self-contained snapshot: (geometry map, dense-state blob) as an
        ETF term — a restarted worker (or another site) rebuilds the grid
        from the blob alone."""
        from ..core import etf, serial

        geom = {Atom(k): v for k, v in self.geometry.items()}
        return etf.encode(
            (geom, serial.dumps_dense(self.type_name, self.state))
        )

    @classmethod
    def from_binary(cls, blob: bytes) -> "_Grid":
        import jax

        from ..core import etf, serial

        term = etf.decode(blob)
        if not (isinstance(term, tuple) and len(term) == 2):
            raise ValueError("grid snapshot must be a (geometry, state) pair")
        geom, state_blob = term
        # The dense-state blob's own header names the type (dumps_dense),
        # so the snapshot tuple stays the frozen 2-element layout the
        # round-2 golden bytes pin while carrying any grid type.
        grid = cls(serial.peek_name(state_blob), dict(geom))
        # (No name re-check here: loads_dense parses the SAME header
        # peek_name dispatched on; the guard that does real work is the
        # shape-vs-geometry validation below.)
        _name, state = serial.loads_dense(state_blob, grid.state)
        for got, like in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(grid.state)
        ):
            if got.shape != like.shape:
                raise ValueError(
                    f"snapshot leaf shape {got.shape} != geometry {like.shape}"
                )
        grid.state = state
        return grid

    def apply(self, per_replica_ops) -> int:
        """Apply one op batch per replica row in one device dispatch.

        Wire op formats (tagged tuples; topk_rmv's is frozen by golden
        bytes, the rest are the round-3 widening of the grid surface):
          topk_rmv         {add, Key, Id, Score, Dc, Ts} | {rmv, Key, Id, [{Dc,Ts}]}
          topk             {add, Key, Id, Score}
          leaderboard      {add, Key, Id, Score} | {ban, Key, Id}
          average          {add, Key, Value, Count}
          wordcount(+doc)  {add, Key, TokenId}   (ids from the host's encoder)
          worddocumentcount also {doc_add, Key, Doc, Uniq, Token} — raw
                           records, per-document dedup on device; whole
                           batch must be doc_add (_apply_worddocumentcount)
        Returns the extras count (dominated elements for topk_rmv, 0 for
        types without extra-op output on this surface)."""
        if len(per_replica_ops) != self.R:
            raise ValueError(f"expected {self.R} replica op lists")
        return getattr(self, f"_apply_{self.type_name}")(per_replica_ops)

    def apply_extras(self, per_replica_ops):
        """Like `apply`, but return the generated extra effect ops per
        replica (one list per replica row) instead of a count — the
        reference's update/2 extras surface (antidote_ccrdt.erl:37-40)
        over the grid wire, each extra in the grid's OWN op shape so it
        feeds straight back into `apply`. topk_rmv yields dominated-add
        re-broadcast removals + rmv-driven promotion adds; leaderboard
        yields ban-promotion adds; the other types generate no extras
        (registry generates_extra_operations) and return empty lists."""
        if len(per_replica_ops) != self.R:
            raise ValueError(f"expected {self.R} replica op lists")
        if self.type_name == "topk_rmv":
            return self._apply_topk_rmv(per_replica_ops, want_extras=True)
        if self.type_name == "leaderboard":
            return self._apply_leaderboard(per_replica_ops, want_extras=True)
        self.apply(per_replica_ops)
        return [[] for _ in range(self.R)]

    # -- packed-columns surface (round 4) ---------------------------------

    def apply_packed(self, groups) -> int:
        """`apply` fed by the packed-columns wire (`_PACKED_COLUMNS`):
        one {Tag, CountsBin, [ColBin...]} group per op kind, columns as
        i32-LE binaries concatenated in replica order. Validation is the
        same loud boundary checking as the tuple packers, vectorized;
        the engine sees identical op batches (differentially pinned by
        tests/test_bridge_packed.py)."""
        return getattr(self, f"_packed_{self.type_name}")(
            self._parse_packed(groups)
        )

    def apply_packed_multi(self, batches) -> int:
        """Multi-batch packed apply in one wire call, SCAN-FUSED for all
        six types: every batch is parsed and range-validated up front
        (all-or-nothing — a bad batch anywhere rejects the call with the
        grid untouched), the op planes are padded to a common bucketed
        width and stacked, and the sequential rounds run as ONE lax.scan
        dispatch — one host->device upload, one dispatch, and one
        extras-count readback per call instead of one of each per batch
        (measured r5 on topk_rmv at the bench shape: per-call dispatch
        ~10% of the same-shape device-native rate, per-batch deferred
        dispatches 19%, scan-fused 25-36%, at which point the residual
        is the op-plane upload bandwidth itself — see bench_all's
        decomposition fields). Returns the total extras count (topk_rmv
        dominated elements; 0 for the others on this surface).

        worddocumentcount accepts either all-doc_add or all-token
        batches in one call; mixing modes across batches falls back to
        validated sequential applies (each mode's dedup is batch-scoped
        either way) — on THAT fallback path only, a range failure inside
        batch k aborts with batches 0..k-1 applied and says so in the
        error, the same bound as k sequential calls; every uniform-mode
        call keeps the all-or-nothing guarantee."""
        if not batches:
            return 0
        parsed_all = []
        for k, groups in enumerate(batches):
            try:
                parsed_all.append(self._parse_packed(groups))
            except Exception as e:
                raise ValueError(
                    f"batch {k} (no batch applied): {e}"
                ) from e

        kind, build = self.type_name, None
        if kind == "topk_rmv":
            build = lambda p: self._build_topk_rmv_arrays(p)[1]  # noqa: E731
        elif kind == "worddocumentcount":
            modes = ["doc" if "doc_add" in p else "wc" for p in parsed_all]
            for k, p in enumerate(parsed_all):
                if "doc_add" in p and "add" in p:
                    raise ValueError(
                        f"batch {k} (no batch applied): batch mixes "
                        "doc_add with other ops"
                    )
            if len(set(modes)) > 1:
                total = 0
                for k, parsed in enumerate(parsed_all):
                    try:
                        total += self._packed_worddocumentcount(parsed)
                    except Exception as e:
                        raise ValueError(
                            f"batch {k} ({k} batch(es) already applied): {e}"
                        ) from e
                return total
            if modes[0] == "doc":
                kind, build = "worddoc_doc", self._build_worddoc_arrays
            else:
                kind, build = "wordcount", self._build_wordcount_arrays
        if build is None:
            build = getattr(self, f"_build_{kind}_arrays")

        builds = []
        for k, parsed in enumerate(parsed_all):
            try:
                builds.append(build(parsed))
            except Exception as e:
                raise ValueError(
                    f"batch {k} (no batch applied): {e}"
                ) from e

        if (
            kind == "topk_rmv"
            and self.NK * self.dense.I * self.dense.D < _PACKED_IDS_LIMIT
        ):
            # Upload-byte packing (the surface's measured binding
            # constraint): (key, id, dc) -> one i32 per add, (key, id) ->
            # one i32 per rmv; unpacked on device by the scan step.
            kind = "topk_rmv_packed_ids"
            I, D = self.dense.I, self.dense.D

            def pack(b):
                a_key, a_id, a_score, a_dc, a_ts, r_key, r_id, r_vc = b
                kid_dc = (a_key.astype(np.int64) * I + a_id) * D + a_dc
                rk = np.where(
                    r_id < 0, -1, r_key.astype(np.int64) * I + r_id
                )
                return (
                    kid_dc.astype(np.int32), a_score, a_ts,
                    rk.astype(np.int32), r_vc,
                )

            builds = [pack(b) for b in builds]

        # Pad each plane to its own bucketed max width across batches
        # (power of two >= 64 bounds the compiled-variant count), with
        # the plane's semantically-inert fill, then stack for the scan.
        fills = _MULTI_FILLS[kind]

        def bucket(n):
            w = 64
            while w < n:
                w *= 2
            return w

        def pad(x, w, fill):
            if x.shape[1] == w:
                return x
            widths = [(0, 0), (0, w - x.shape[1])] + [(0, 0)] * (x.ndim - 2)
            return np.pad(x, widths, constant_values=fill)

        widths = [
            bucket(max(b[i].shape[1] for b in builds))
            for i in range(len(fills))
        ]
        stacked = tuple(
            np.stack([pad(b[i], widths[i], fills[i]) for b in builds])
            for i in range(len(fills))
        )
        self.state, total = _get_scan_fn(kind)(self.dense, self.state, stacked)
        return int(np.asarray(total))

    def apply_extras_packed(self, groups):
        """`apply_extras` over the packed wire: same input form as
        `apply_packed`; the reply is the generated extras as packed
        groups in the grid's OWN packed column orders, so a host feeds
        them straight back into `grid_apply_packed`. topk_rmv replies
        a {rmv, ...} group (dominated-add re-broadcast vcs) + an
        {add, ...} group (rmv-driven promotions); leaderboard an {add,
        ...} group (ban promotions); other types reply []."""
        parsed = self._parse_packed(groups)
        if self.type_name == "topk_rmv":
            return self._packed_topk_rmv(parsed, want_extras=True)
        if self.type_name == "leaderboard":
            return self._packed_leaderboard(parsed, want_extras=True)
        getattr(self, f"_packed_{self.type_name}")(parsed)
        return []

    def _parse_packed(self, groups):
        parsed: Dict[str, Tuple[np.ndarray, Dict[str, np.ndarray]]] = {}
        for g in groups:
            if not (isinstance(g, tuple) and len(g) == 3):
                raise ValueError("packed group must be {Tag, Counts, Cols}")
            tag, counts_bin, col_bins = g
            tag = str(tag)
            spec = _PACKED_COLUMNS.get((self.type_name, tag))
            if spec is None:
                raise ValueError(f"unknown grid op tag: {tag!r}")
            if tag in parsed:
                raise ValueError(f"duplicate packed group for tag {tag!r}")
            counts = _i32_col(counts_bin, f"{tag} counts")
            if counts.size != self.R:
                raise ValueError(
                    f"expected {self.R} replica op counts, got {counts.size}"
                )
            if (counts < 0).any():
                raise ValueError(f"negative op count in {tag} group")
            if len(col_bins) != len(spec):
                raise ValueError(
                    f"{tag} expects columns {list(spec)}, got "
                    f"{len(col_bins)} binaries"
                )
            cols = {
                name: _i32_col(b, f"{tag}.{name}")
                for name, b in zip(spec, col_bins)
            }
            total = int(counts.sum())
            for name, col in cols.items():
                want = (
                    int(cols["vc_len"].sum())
                    if name in ("vc_dc", "vc_ts") else total
                )
                if col.size != want:
                    raise ValueError(
                        f"{tag}.{name} has {col.size} values, expected {want}"
                    )
            parsed[tag] = (counts, cols)
        return parsed

    def _pad_cols(self, counts: np.ndarray, cols, fills):
        """Scatter concatenated ragged columns into padded [R, B] arrays
        (B = longest replica batch; also returns the per-op (r, j)
        coordinates for ragged sub-structures like rmv vcs)."""
        B = max(1, int(counts.max(initial=0)))
        r_idx = np.repeat(np.arange(self.R), counts)
        starts = np.cumsum(counts) - counts
        j_idx = np.arange(int(counts.sum())) - np.repeat(starts, counts)
        out = []
        for col, fill in zip(cols, fills):
            arr = np.full((self.R, B), fill, np.int32)
            arr[r_idx, j_idx] = col
            out.append(arr)
        return B, r_idx, j_idx, out

    def _build_average_arrays(self, parsed):
        counts, cols = parsed.get("add", (np.zeros(self.R, np.int32), {}))
        k = cols.get("key", np.zeros(0, np.int32))
        _reject(~((0 <= k) & (k < self.NK)), k, "add key={} out of range")
        c = cols.get("count", np.zeros(0, np.int32))
        _reject(c < 0, c, "add count={} out of range")
        _, _, _, (key, val, cnt) = self._pad_cols(
            counts,
            (k, cols.get("value", np.zeros(0, np.int32)), c),
            (0, 0, 0),
        )
        return key, val, cnt

    def _packed_average(self, parsed) -> int:
        import jax.numpy as jnp

        from ..models.average import AverageOps

        key, val, cnt = self._build_average_arrays(parsed)
        self.state, _ = self.dense.apply_ops(
            self.state,
            AverageOps(
                key=jnp.asarray(key), value=jnp.asarray(val),
                count=jnp.asarray(cnt),
            ),
        )
        return 0

    def _build_topk_arrays(self, parsed):
        counts, cols = parsed.get("add", (np.zeros(self.R, np.int32), {}))
        k = cols.get("key", np.zeros(0, np.int32))
        i = cols.get("id", np.zeros(0, np.int32))
        bad = ~((0 <= k) & (k < self.NK) & (0 <= i) & (i < self.dense.I))
        if bad.any():
            j = int(np.argmax(bad))
            raise ValueError(f"add (key={k[j]}, id={i[j]}) out of range")
        _, r_idx, j_idx, (key, id_, score) = self._pad_cols(
            counts, (k, i, cols.get("score", np.zeros(0, np.int32))), (0, 0, 0)
        )
        valid = np.zeros(key.shape, bool)
        valid[r_idx, j_idx] = True
        return key, id_, score, valid

    def _packed_topk(self, parsed) -> int:
        import jax.numpy as jnp

        from ..models.topk import TopkOps

        key, id_, score, valid = self._build_topk_arrays(parsed)
        self.state, _ = self.dense.apply_ops(
            self.state,
            TopkOps(
                key=jnp.asarray(key), id=jnp.asarray(id_),
                score=jnp.asarray(score), valid=jnp.asarray(valid),
            ),
        )
        return 0

    def _build_leaderboard_arrays(self, parsed):
        P = self.dense.P
        padded = {}
        for tag, names in (("add", ("key", "id", "score")), ("ban", ("key", "id"))):
            counts, cols = parsed.get(tag, (np.zeros(self.R, np.int32), {}))
            k = cols.get("key", np.zeros(0, np.int32))
            i = cols.get("id", np.zeros(0, np.int32))
            bad = ~((0 <= k) & (k < self.NK) & (0 <= i) & (i < P))
            if bad.any():
                j = int(np.argmax(bad))
                raise ValueError(f"{tag} (key={k[j]}, id={i[j]}) out of range")
            vals = [cols.get(n, np.zeros(0, np.int32)) for n in names]
            _, r_idx, j_idx, arrs = self._pad_cols(
                counts, vals, (0,) * len(names)
            )
            valid = np.zeros(arrs[0].shape, bool)
            valid[r_idx, j_idx] = True
            padded[tag] = (*arrs, valid)
        return padded["add"] + padded["ban"]

    def _packed_leaderboard(self, parsed, want_extras: bool = False):
        import jax.numpy as jnp

        from ..models.leaderboard import LeaderboardOps

        (a_key, a_id, a_score, a_valid, b_key, b_id, b_valid) = (
            self._build_leaderboard_arrays(parsed)
        )
        self.state, promoted = self.dense.apply_ops(
            self.state,
            LeaderboardOps(
                add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
                add_score=jnp.asarray(a_score), add_valid=jnp.asarray(a_valid),
                ban_key=jnp.asarray(b_key), ban_id=jnp.asarray(b_id),
                ban_valid=jnp.asarray(b_valid),
            ),
            collect_promotions=want_extras,
        )
        if not want_extras:
            return 0
        # Ban-promotion extras as a packed {add, ...} reply group —
        # same (r, k, j) emission order as the term surface.
        ids, scores, keep = (np.asarray(x) for x in promoted)
        rr, kk, jj = np.nonzero(keep)
        p_counts = keep.reshape(self.R, -1).sum(axis=1)
        return [(Atom("add"), _bin_col(p_counts), [
            _bin_col(kk), _bin_col(ids[rr, kk, jj]),
            _bin_col(scores[rr, kk, jj]),
        ])]

    def _build_wordcount_arrays(self, parsed):
        counts, cols = parsed.get("add", (np.zeros(self.R, np.int32), {}))
        k = cols.get("key", np.zeros(0, np.int32))
        t = cols.get("token", np.zeros(0, np.int32))
        _reject(~((0 <= k) & (k < self.NK)), k, "add key={} out of range")
        _reject(~((0 <= t) & (t < self.dense.V)), t, "add token={} out of range")
        _, _, _, (key, tok) = self._pad_cols(counts, (k, t), (0, -1))
        return key, tok

    def _packed_wordcount(self, parsed) -> int:
        import jax.numpy as jnp

        from ..models.wordcount import WordcountOps

        key, tok = self._build_wordcount_arrays(parsed)
        self.state, _ = self.dense.apply_ops(
            self.state,
            WordcountOps(key=jnp.asarray(key), token=jnp.asarray(tok)),
        )
        return 0

    def _build_worddoc_arrays(self, parsed):
        counts, cols = parsed["doc_add"]
        k, d = cols["key"], cols["doc"]
        u, t = cols["uniq"], cols["token"]
        _reject(~((0 <= k) & (k < self.NK)), k, "doc_add key={} out of range")
        _reject(
            ~((0 <= t) & (t < self.dense.V)), t, "doc_add token={} out of range"
        )
        if ((d < 0) | (u < 0)).any():
            j = int(np.argmax((d < 0) | (u < 0)))
            raise ValueError(f"doc_add doc={d[j]}/uniq={u[j]} negative")
        _, _, _, (key, doc, uniq, tok) = self._pad_cols(
            counts, (k, d, u, t), (0, 0, 0, -1)
        )
        return key, doc, uniq, tok

    def _packed_worddocumentcount(self, parsed) -> int:
        import jax.numpy as jnp

        from ..models.wordcount import WordDocOps

        if "doc_add" not in parsed:
            return self._packed_wordcount(parsed)
        if "add" in parsed:
            raise ValueError(
                "grid_apply batch mixes doc_add with other ops; the "
                "per-document dedup is batch-scoped — send one mode per "
                "batch"
            )
        key, doc, uniq, tok = self._build_worddoc_arrays(parsed)
        self.state, _ = self.dense.apply_doc_ops(
            self.state,
            WordDocOps(
                key=jnp.asarray(key), doc=jnp.asarray(doc),
                uniq=jnp.asarray(uniq), token=jnp.asarray(tok),
            ),
        )
        return 0

    def _build_topk_rmv_arrays(self, parsed):
        """Validation + column->batch-array packing for the topk_rmv
        packed wire, WITHOUT the device dispatch: returns the eight
        numpy op planes (a_key, a_id, a_score, a_dc, a_ts, r_key, r_id,
        r_vc) plus a_counts for the extras reply. Shared by the
        single-batch dispatch and the scan-fused multi path (which must
        validate every batch before dispatching any)."""
        D, I, NK = self.dense.D, self.dense.I, self.NK
        a_counts, a_cols = parsed.get("add", (np.zeros(self.R, np.int32), {}))
        ak = a_cols.get("key", np.zeros(0, np.int32))
        ai = a_cols.get("id", np.zeros(0, np.int32))
        adc = a_cols.get("dc", np.zeros(0, np.int32))
        ats = a_cols.get("ts", np.zeros(0, np.int32))
        _reject(~((0 <= adc) & (adc < D)), adc, "dc {} out of range")
        bad = ~((0 <= ak) & (ak < NK) & (0 <= ai) & (ai < I))
        if bad.any():
            j = int(np.argmax(bad))
            raise ValueError(f"add (key={ak[j]}, id={ai[j]}) out of range")
        _reject(ats < 1, ats, "add ts {} out of range (ts >= 1)")
        _, _, _, (a_key, a_id, a_score, a_dc, a_ts) = self._pad_cols(
            a_counts,
            (ak, ai, a_cols.get("score", np.zeros(0, np.int32)), adc, ats),
            (0, 0, 0, 0, 0),
        )

        r_counts, r_cols = parsed.get("rmv", (np.zeros(self.R, np.int32), {}))
        rk = r_cols.get("key", np.zeros(0, np.int32))
        ri_ = r_cols.get("id", np.zeros(0, np.int32))
        bad = ~((0 <= rk) & (rk < NK) & (0 <= ri_) & (ri_ < I))
        if bad.any():
            j = int(np.argmax(bad))
            raise ValueError(f"rmv (key={rk[j]}, id={ri_[j]}) out of range")
        vc_len = r_cols.get("vc_len", np.zeros(0, np.int32))
        if (vc_len < 0).any():
            raise ValueError("rmv vc_len negative")
        vc_dc = r_cols.get("vc_dc", np.zeros(0, np.int32))
        vc_ts = r_cols.get("vc_ts", np.zeros(0, np.int32))
        _reject(~((0 <= vc_dc) & (vc_dc < D)), vc_dc, "dc {} out of range")
        Br, r_idx, j_idx, (r_key, r_id) = self._pad_cols(
            r_counts, (rk, ri_), (0, -1)
        )
        r_vc = np.zeros((self.R, Br, D), np.int32)
        if vc_dc.size:
            op_of_vc = np.repeat(np.arange(ri_.size), vc_len)
            # Last-wins for duplicate dcs within one op's vc list, matching
            # the tuple path's sequential overwrite — made explicit, since
            # NumPy does not guarantee assignment order for repeated fancy
            # indices: keep only the final (op, dc) entry per pair.
            pair = op_of_vc.astype(np.int64) * D + vc_dc
            _, first_in_rev = np.unique(pair[::-1], return_index=True)
            keep = pair.size - 1 - first_in_rev
            r_vc[r_idx[op_of_vc[keep]], j_idx[op_of_vc[keep]], vc_dc[keep]] = (
                vc_ts[keep]
            )
        return (
            a_counts,
            (a_key, a_id, a_score, a_dc, a_ts, r_key, r_id, r_vc),
        )

    def _packed_topk_rmv(self, parsed, want_extras: bool = False):
        import jax.numpy as jnp

        from ..models.topk_rmv_dense import TopkRmvOps

        D = self.dense.D
        a_counts, arrays = self._build_topk_rmv_arrays(parsed)
        a_key, a_id, a_score, a_dc, a_ts, r_key, r_id, r_vc = arrays

        self.state, extras = self.dense.apply_ops(
            self.state,
            TopkRmvOps(
                add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
                add_score=jnp.asarray(a_score), add_dc=jnp.asarray(a_dc),
                add_ts=jnp.asarray(a_ts),
                rmv_key=jnp.asarray(r_key), rmv_id=jnp.asarray(r_id),
                rmv_vc=jnp.asarray(r_vc),
            ),
            collect_promotions=want_extras,
        )
        if not want_extras:
            # Device-side scalar sum: one scalar readback instead of
            # pulling the whole [R, B] mask to the host.
            return int(np.asarray(jnp.sum(extras.dominated)))
        # Dominated-add re-broadcast rmvs as a packed {rmv, ...} group —
        # emission order (replica-major, op order) matches the term
        # surface; the vc rows are the op-aligned dominated_vc rows with
        # zero entries elided, exactly like the term path's vc_list.
        dom = np.asarray(extras.dominated)
        dvc = np.asarray(extras.dominated_vc)
        live = np.arange(dom.shape[1])[None, :] < a_counts[:, None]
        mask = dom & live
        r_sel, j_sel = np.nonzero(mask)
        rows = dvc[r_sel, j_sel]  # [n_dom, D]
        nz = rows > 0
        rmv_group = (Atom("rmv"), _bin_col(mask.sum(axis=1)), [
            _bin_col(a_key[r_sel, j_sel]), _bin_col(a_id[r_sel, j_sel]),
            _bin_col(nz.sum(axis=1)),
            _bin_col(np.broadcast_to(
                np.arange(D, dtype=np.int32), rows.shape
            )[nz]),
            _bin_col(rows[nz]),
        ])
        # Promotion adds (rmv-uncovered elements), (r, k, j) order like
        # the term loop.
        pr = extras.promoted
        pids, pscores, pdcs, ptss, keep = (
            np.asarray(x) for x in (pr.ids, pr.scores, pr.dcs, pr.tss, pr.valid)
        )
        rr, kk, jj = np.nonzero(keep)
        add_group = (Atom("add"), _bin_col(keep.reshape(self.R, -1).sum(axis=1)), [
            _bin_col(kk), _bin_col(pids[rr, kk, jj]),
            _bin_col(pscores[rr, kk, jj]), _bin_col(pdcs[rr, kk, jj]),
            _bin_col(ptss[rr, kk, jj]),
        ])
        return [rmv_group, add_group]

    @staticmethod
    def _check_tags(per_replica_ops, allowed) -> None:
        for ops in per_replica_ops:
            for op in ops:
                if op[0] not in allowed:
                    raise ValueError(f"unknown grid op tag: {op[0]!r}")

    def _apply_topk_rmv(self, per_replica_ops, want_extras: bool = False):
        import jax.numpy as jnp

        from ..models.topk_rmv_dense import TopkRmvOps

        D = self.dense.D
        self._check_tags(per_replica_ops, (Atom("add"), Atom("rmv")))
        adds = [[op for op in ops if op[0] == Atom("add")] for ops in per_replica_ops]
        rmvs = [[op for op in ops if op[0] == Atom("rmv")] for ops in per_replica_ops]
        B = max(1, max(len(a) for a in adds))
        Br = max(1, max(len(r) for r in rmvs))
        a = np.zeros((self.R, B, 5), np.int32)  # key,id,score,dc,ts (ts=0 pad)
        r_key = np.zeros((self.R, Br), np.int32)
        r_id = np.full((self.R, Br), -1, np.int32)
        r_vc = np.zeros((self.R, Br, D), np.int32)
        I, NK = self.dense.I, self.NK
        for ri, ops in enumerate(adds):
            for j, (_, key, id_, score, dc, ts) in enumerate(ops):
                if not 0 <= dc < D:
                    # An out-of-range add dc would create an element no
                    # tombstone can ever dominate (the filter's select-scan
                    # never matches it) — reject rather than immortalize.
                    raise ValueError(f"dc {dc} out of range")
                if not (0 <= key < NK and 0 <= id_ < I):
                    # The dense kernels index with clamping gathers /
                    # mode='drop' scatters: an out-of-range id would read the
                    # wrong element's tombstones and then be silently
                    # discarded — reject at the boundary instead.
                    raise ValueError(f"add (key={key}, id={id_}) out of range")
                if ts < 1:
                    # ts == 0 is the dense engines' empty-slot sentinel: the
                    # add would be silently treated as padding and its dc
                    # dropped from re-broadcast vcs (reference add/2 returns
                    # the full removal vc, topk_rmv.erl:234-237). Enforce the
                    # repo-wide "real timestamps start at 1" convention
                    # loudly at the wire, like the other field checks
                    # (ADVICE r3 #3).
                    raise ValueError(f"add ts {ts} out of range (ts >= 1)")
                a[ri, j] = (key, id_, score, dc, ts)
        for ri, ops in enumerate(rmvs):
            for j, (_, key, id_, vc_list) in enumerate(ops):
                if not (0 <= key < NK and 0 <= id_ < I):
                    raise ValueError(f"rmv (key={key}, id={id_}) out of range")
                r_key[ri, j] = key
                r_id[ri, j] = id_
                for dc, ts in vc_list:
                    if not 0 <= dc < D:
                        raise ValueError(f"dc {dc} out of range")
                    r_vc[ri, j, dc] = ts
        ops_batch = TopkRmvOps(
            add_key=jnp.asarray(a[:, :, 0]),
            add_id=jnp.asarray(a[:, :, 1]),
            add_score=jnp.asarray(a[:, :, 2]),
            add_dc=jnp.asarray(a[:, :, 3]),
            add_ts=jnp.asarray(a[:, :, 4]),
            rmv_key=jnp.asarray(r_key),
            rmv_id=jnp.asarray(r_id),
            rmv_vc=jnp.asarray(r_vc),
        )
        self.state, extras = self.dense.apply_ops(
            self.state, ops_batch, collect_promotions=want_extras
        )
        if not want_extras:
            return int(np.asarray(extras.dominated).sum())
        # Re-broadcast removals for dominated adds (topk_rmv.erl:234-237):
        # op-aligned {rmv, Key, Id, VcList} terms, same shape the rmv
        # INPUT op uses — the host feeds them straight back into
        # replication.
        dom = np.asarray(extras.dominated)
        dvc = np.asarray(extras.dominated_vc)
        out = []
        for ri, ops in enumerate(adds):
            row = []
            for j in range(len(ops)):
                if dom[ri, j]:
                    vc_list = [
                        (int(d), int(t))
                        for d, t in enumerate(dvc[ri, j])
                        if t > 0
                    ]
                    row.append(
                        (Atom("rmv"), int(a[ri, j, 0]), int(a[ri, j, 1]),
                         vc_list)
                    )
            out.append(row)
        # Promotion extras (reference :291-295): removals that uncover a
        # masked element re-broadcast it as a plain add {add, Key, Id,
        # Score, Dc, Ts} — the grid's own add op shape, feedable straight
        # back (scalar parity: _rmv returns ("add", (i, s, t))).
        pids = np.asarray(extras.promoted.ids)
        pscores = np.asarray(extras.promoted.scores)
        pdcs = np.asarray(extras.promoted.dcs)
        ptss = np.asarray(extras.promoted.tss)
        pkeep = np.asarray(extras.promoted.valid)
        for ri in range(self.R):
            for k in range(self.NK):
                for j in np.nonzero(pkeep[ri, k])[0]:
                    out[ri].append(
                        (Atom("add"), int(k), int(pids[ri, k, j]),
                         int(pscores[ri, k, j]), int(pdcs[ri, k, j]),
                         int(ptss[ri, k, j]))
                    )
        return out

    def _apply_topk(self, per_replica_ops) -> int:
        import jax.numpy as jnp

        from ..models.topk import TopkOps

        self._check_tags(per_replica_ops, (Atom("add"),))
        I, NK = self.dense.I, self.NK
        B = max(1, max(len(ops) for ops in per_replica_ops))
        key = np.zeros((self.R, B), np.int32)
        id_ = np.zeros((self.R, B), np.int32)
        score = np.zeros((self.R, B), np.int32)
        valid = np.zeros((self.R, B), bool)
        for ri, ops in enumerate(per_replica_ops):
            for j, (_, k, i, s) in enumerate(ops):
                if not (0 <= k < NK and 0 <= i < I):
                    raise ValueError(f"add (key={k}, id={i}) out of range")
                key[ri, j], id_[ri, j], score[ri, j] = k, i, s
                valid[ri, j] = True
        self.state, _ = self.dense.apply_ops(
            self.state,
            TopkOps(
                key=jnp.asarray(key), id=jnp.asarray(id_),
                score=jnp.asarray(score), valid=jnp.asarray(valid),
            ),
        )
        return 0

    def _apply_leaderboard(self, per_replica_ops, want_extras: bool = False):
        import jax.numpy as jnp

        from ..models.leaderboard import LeaderboardOps

        self._check_tags(per_replica_ops, (Atom("add"), Atom("ban")))
        P, NK = self.dense.P, self.NK
        adds = [[op for op in ops if op[0] == Atom("add")] for ops in per_replica_ops]
        bans = [[op for op in ops if op[0] == Atom("ban")] for ops in per_replica_ops]
        B = max(1, max(len(a) for a in adds))
        Bb = max(1, max(len(b) for b in bans))
        a_key = np.zeros((self.R, B), np.int32)
        a_id = np.zeros((self.R, B), np.int32)
        a_score = np.zeros((self.R, B), np.int32)
        a_valid = np.zeros((self.R, B), bool)
        b_key = np.zeros((self.R, Bb), np.int32)
        b_id = np.zeros((self.R, Bb), np.int32)
        b_valid = np.zeros((self.R, Bb), bool)
        for ri, ops in enumerate(adds):
            for j, (_, k, i, s) in enumerate(ops):
                if not (0 <= k < NK and 0 <= i < P):
                    raise ValueError(f"add (key={k}, id={i}) out of range")
                a_key[ri, j], a_id[ri, j], a_score[ri, j] = k, i, s
                a_valid[ri, j] = True
        for ri, ops in enumerate(bans):
            for j, (_, k, i) in enumerate(ops):
                if not (0 <= k < NK and 0 <= i < P):
                    raise ValueError(f"ban (key={k}, id={i}) out of range")
                b_key[ri, j], b_id[ri, j] = k, i
                b_valid[ri, j] = True
        ops_batch = LeaderboardOps(
            add_key=jnp.asarray(a_key), add_id=jnp.asarray(a_id),
            add_score=jnp.asarray(a_score), add_valid=jnp.asarray(a_valid),
            ban_key=jnp.asarray(b_key), ban_id=jnp.asarray(b_id),
            ban_valid=jnp.asarray(b_valid),
        )
        self.state, promoted = self.dense.apply_ops(
            self.state, ops_batch, collect_promotions=want_extras
        )
        if not want_extras:
            return 0
        # Ban-promotion extras (leaderboard.erl:279-283): entries newly
        # visible that this batch's adds don't explain — re-broadcast as
        # plain adds {add, Key, Id, Score}, the grid's own op shape, so
        # the host can feed them straight back (the scalar reference's
        # update likewise returns ("add", new_elem); the replicate-tagged
        # add_r distinction is an inter-DC shipping concern the scalar
        # surface's is_replicate_tagged covers).
        ids, scores, keep = (np.asarray(x) for x in promoted)
        out = []
        for ri in range(self.R):
            row = []
            for k in range(self.NK):
                for j in np.nonzero(keep[ri, k])[0]:
                    row.append(
                        (Atom("add"), int(k), int(ids[ri, k, j]),
                         int(scores[ri, k, j]))
                    )
            out.append(row)
        return out

    def _apply_average(self, per_replica_ops) -> int:
        import jax.numpy as jnp

        from ..models.average import AverageOps

        self._check_tags(per_replica_ops, (Atom("add"),))
        NK = self.NK
        B = max(1, max(len(ops) for ops in per_replica_ops))
        key = np.zeros((self.R, B), np.int32)
        val = np.zeros((self.R, B), np.int32)
        cnt = np.zeros((self.R, B), np.int32)
        for ri, ops in enumerate(per_replica_ops):
            for j, (_, k, v, c) in enumerate(ops):
                if not 0 <= k < NK:
                    raise ValueError(f"add key={k} out of range")
                if c < 0:
                    # count==0 is the engine's padding sentinel; a negative
                    # count has no reference semantics (average.erl:87-89).
                    raise ValueError(f"add count={c} out of range")
                key[ri, j], val[ri, j], cnt[ri, j] = k, v, c
        self.state, _ = self.dense.apply_ops(
            self.state,
            AverageOps(
                key=jnp.asarray(key), value=jnp.asarray(val),
                count=jnp.asarray(cnt),
            ),
        )
        return 0

    def _apply_wordcount(self, per_replica_ops) -> int:
        import jax.numpy as jnp

        from ..models.wordcount import WordcountOps

        self._check_tags(per_replica_ops, (Atom("add"),))
        NK, V = self.NK, self.dense.V
        B = max(1, max(len(ops) for ops in per_replica_ops))
        key = np.zeros((self.R, B), np.int32)
        tok = np.full((self.R, B), -1, np.int32)  # token<0 = padding
        for ri, ops in enumerate(per_replica_ops):
            for j, (_, k, t) in enumerate(ops):
                if not 0 <= k < NK:
                    raise ValueError(f"add key={k} out of range")
                if not 0 <= t < V:
                    # Over-table ids would silently land in the lost
                    # counter; the wire is the place to be loud.
                    raise ValueError(f"add token={t} out of range")
                key[ri, j], tok[ri, j] = k, t
        self.state, _ = self.dense.apply_ops(
            self.state,
            WordcountOps(key=jnp.asarray(key), token=jnp.asarray(tok)),
        )
        return 0

    def _apply_worddocumentcount(self, per_replica_ops) -> int:
        """Two op shapes: {add, Key, Token} (host already deduped — the
        shared wordcount packer) or {doc_add, Key, Doc, Uniq, Token} (raw
        per-token records; the per-document dedup runs ON DEVICE as one
        sort over the batch, worddocumentcount.erl:76-86 semantics via
        apply_doc_ops — `Uniq` is the string-identity id, so hash-
        colliding distinct words still count twice in a shared bucket).
        A batch is one mode or the other: dedup is batch-scoped, and a
        document's records must not split across grid_apply calls."""
        import jax.numpy as jnp

        from ..models.wordcount import WordDocOps

        tags = {op[0] for ops in per_replica_ops for op in ops}
        if Atom("doc_add") not in tags:
            return self._apply_wordcount(per_replica_ops)
        if tags != {Atom("doc_add")}:
            raise ValueError(
                "grid_apply batch mixes doc_add with other ops; the "
                "per-document dedup is batch-scoped — send one mode per "
                "batch"
            )
        NK, V = self.NK, self.dense.V
        B = max(1, max(len(ops) for ops in per_replica_ops))
        key = np.zeros((self.R, B), np.int32)
        doc = np.zeros((self.R, B), np.int32)
        uniq = np.zeros((self.R, B), np.int32)
        tok = np.full((self.R, B), -1, np.int32)  # token<0 = padding
        for ri, ops in enumerate(per_replica_ops):
            for j, (_, k, d, u, t) in enumerate(ops):
                if not 0 <= k < NK:
                    raise ValueError(f"doc_add key={k} out of range")
                if not 0 <= t < V:
                    raise ValueError(f"doc_add token={t} out of range")
                if d < 0 or u < 0:
                    raise ValueError(f"doc_add doc={d}/uniq={u} negative")
                key[ri, j], doc[ri, j], uniq[ri, j], tok[ri, j] = k, d, u, t
        self.state, _ = self.dense.apply_doc_ops(
            self.state,
            WordDocOps(
                key=jnp.asarray(key), doc=jnp.asarray(doc),
                uniq=jnp.asarray(uniq), token=jnp.asarray(tok),
            ),
        )
        return 0

    def merge_all(self) -> None:
        """One-dispatch inter-DC reconciliation, by merge algebra:

        JOIN — fold all replica rows with the lattice join and broadcast
        the result back (idempotent: every DC now holds the full join).

        MONOID — per-replica rows are DELTAS (MergeKind docstring), so
        broadcasting a fold would multiply the total by R on the next
        fold. Instead the fold lands in row 0 and the other rows reset to
        the monoid identity: the grid total is preserved, merge_all is
        idempotent at the total level, and later ops keep accumulating."""
        import jax
        import jax.numpy as jnp

        from ..core.behaviour import MergeKind

        state = self.state
        r = self.R
        while r > 1:
            half = r // 2
            top = jax.tree.map(lambda x: x[:half], state)
            bot = jax.tree.map(lambda x: x[half : 2 * half], state)
            merged = self.dense.merge(top, bot)
            if r % 2:
                odd = jax.tree.map(lambda x: x[2 * half : r], state)
                merged = jax.tree.map(
                    lambda m, o: jnp.concatenate([m, o], axis=0), merged, odd
                )
            state = merged
            r = half + (r % 2)
        if getattr(self.dense, "merge_kind", None) == MergeKind.MONOID:
            ident = self.dense.init(n_replicas=self.R - 1, n_keys=self.NK)
            self.state = (
                state
                if self.R == 1
                else jax.tree.map(
                    lambda total, z: jnp.concatenate([total[:1], z], axis=0),
                    state, ident,
                )
            )
        else:
            self.state = jax.tree.map(
                lambda x: jnp.broadcast_to(x[:1], (self.R,) + x.shape[1:]), state
            )

    def observe(self, replica: int, key: int):
        import jax

        if not (0 <= replica < self.R and 0 <= key < self.NK):
            raise ValueError(f"observe ({replica}, {key}) out of range")
        # Slice to the one requested cell before the observe sort — a full
        # dense.value() would sort and host-transfer the whole [R, NK] grid
        # (and hold the server lock while doing it).
        cell = jax.tree.map(lambda x: x[replica : replica + 1, key : key + 1], self.state)
        if self.type_name == "average":
            # {Sum, Num} — lossless; the client derives the float the way
            # the scalar value/1 does (average.erl:38-42).
            return (int(cell.sum[0, 0]), int(cell.num[0, 0]))
        if self.type_name in ("wordcount", "worddocumentcount"):
            counts = np.asarray(cell.counts)[0, 0]
            return [(int(t), int(c)) for t, c in enumerate(counts) if c]
        return [(_to_term(i), s) for (i, s) in self.dense.value(cell)[0][0]]


# --- server ---------------------------------------------------------------


class BridgeServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        read_deadline: Optional[float] = None,
        metrics: Optional[Metrics] = None,
        reply_cache_size: int = 1024,
    ):
        """`read_deadline` (seconds) bounds how long a connection may sit
        idle between frames: a half-open or wedged client releases its
        thread instead of leaking it forever (None = no deadline, the
        historical behavior). `reply_cache_size` bounds the icall
        idempotency cache (see protocol: (token, req_id) -> reply)."""
        self._handles: Dict[Any, Tuple[str, Any]] = {}
        self._grids: Dict[Any, _Grid] = {}
        self._next = 0
        self.metrics = metrics if metrics is not None else Metrics()
        self._read_deadline = read_deadline
        # Lock order: object locks (handles/grids) outrank _meta; _meta is
        # only ever taken alone or inside an already-held object lock.
        self._meta = threading.Lock()
        self._hlocks: Dict[Any, threading.Lock] = {}
        self._glocks: Dict[Any, threading.Lock] = {}
        # icall idempotency: (token, req_id) -> full reply term, LRU.
        # A resent request whose first execution's reply was lost in a
        # reset must NOT execute twice (grid_apply is not idempotent).
        self._replies: "OrderedDict[Tuple[bytes, Any], Any]" = OrderedDict()
        self._replies_cap = reply_cache_size
        self._replies_lock = threading.Lock()
        # Serve plane: {query, Payload} ops route here when installed —
        # the bridge is the third query surface (tcp frame, HTTP POST,
        # and this), all carrying the same canonical bytes.
        self.query_handler = None
        self.write_handler = None
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                buf = bytearray()
                if outer._read_deadline is not None:
                    self.request.settimeout(outer._read_deadline)
                while True:
                    try:
                        chunk = self.request.recv(1 << 16)
                    except socket.timeout:
                        outer.metrics.count("bridge.read_deadline_drops")
                        return
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    for term in P.unpack_frames(buf):
                        self.request.sendall(P.pack_frame(outer._dispatch(term)))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((host, port), Handler)
        self.address = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def install_serve(self, plane) -> None:
        """Attach a serve plane (or any bytes->bytes handler); the
        {query} op starts answering. Mirrors TcpTransport.install_serve."""
        handler_for = getattr(plane, "handler_for", None)
        if callable(handler_for):
            self.query_handler = handler_for("bridge")
        else:
            self.query_handler = getattr(plane, "handle", plane)

    def install_ingest(self, plane) -> None:
        """Attach an ingest plane (or any bytes->bytes handler); the
        {write} op starts answering. Mirrors TcpTransport.install_ingest."""
        handler_for = getattr(plane, "handler_for", None)
        if callable(handler_for):
            self.write_handler = handler_for("bridge")
        else:
            self.write_handler = getattr(plane, "handle", plane)

    # -- dispatch ----------------------------------------------------------

    # Which operand positions hold handles that must be locked, per tag.
    _HANDLE_ARGS = {
        "downstream": (1,), "update": (1,), "value": (1,), "to_binary": (1,),
        "compact": (1,), "equal": (1, 2),
    }
    _GRID_TAGS = {
        "grid_apply", "grid_apply_extras", "grid_apply_packed",
        "grid_apply_extras_packed", "grid_apply_packed_multi",
        "grid_merge_all", "grid_observe", "grid_to_binary",
    }

    def _dispatch(self, term: Any) -> Any:
        token: Optional[bytes] = None
        if (
            isinstance(term, tuple) and len(term) == 4
            and term[0] == P.A_ICALL and isinstance(term[1], (bytes, bytearray))
        ):
            _, token, req_id, op = term
            token = bytes(token)
            with self._replies_lock:
                cached = self._replies.get((token, req_id))
                if cached is not None:
                    self._replies.move_to_end((token, req_id))
                    self.metrics.count("bridge.replays")
                    obs_events.emit(
                        "bridge.request", req_id=req_id, outcome="replay"
                    )
                    return cached
        elif isinstance(term, tuple) and len(term) == 3 and term[0] == P.A_CALL:
            _, req_id, op = term
        else:
            self.metrics.count("bridge.errors")
            obs_events.emit(
                "bridge.request", req_id=-1, outcome="bad_request"
            )
            return P.reply_error(-1, f"bad request: {term!r}", kind="bad_request")
        op_tag = str(op[0]) if isinstance(op, tuple) and op else "?"
        try:
            reply = P.reply_ok(req_id, self._exec_routed(op))
            obs_events.emit(
                "bridge.request", req_id=req_id, op=op_tag, outcome="ok"
            )
        except Exception as e:  # noqa: BLE001 - all errors go to the client,
            # as a STRUCTURED {error, {Kind, Msg}} frame (never silently
            # swallowed): Kind is the exception class for hosts to dispatch
            # on, and the server-side counter makes error volume observable.
            self.metrics.count("bridge.errors")
            self.metrics.count(f"bridge.errors.{type(e).__name__}")
            obs_events.emit(
                "bridge.request",
                req_id=req_id,
                op=op_tag,
                outcome="error",
                error_kind=type(e).__name__,
            )
            return P.reply_error(req_id, str(e), kind=type(e).__name__)
        if token is not None:
            with self._replies_lock:
                self._replies[(token, req_id)] = reply
                while len(self._replies) > self._replies_cap:
                    self._replies.popitem(last=False)
        return reply

    def _exec_routed(self, op: Any) -> Any:
        """Acquire exactly the locks the op needs, then run it."""
        tag = str(op[0])
        if tag == "free":
            try:
                lk = self._handle_lock(op[1])
            except KeyError:
                return True  # already freed — free is idempotent
            with lk:
                return self._exec(op)
        if tag in self._HANDLE_ARGS:
            handles = [op[i] for i in self._HANDLE_ARGS[tag]]
        elif tag == "batch_merge":
            # Lock the handle items; inline binaries need no lock.
            handles = [it for it in op[2] if not isinstance(it, (bytes, bytearray))]
        elif tag in self._GRID_TAGS:
            with self._grid_lock(op[1]):
                return self._exec(op)
        else:
            # new / from_binary / grid_new create objects (inserted under
            # _meta inside _exec); registry predicates are pure reads.
            return self._exec(op)
        # repr-sort = one global acquisition order; dedup because an op may
        # name the same handle twice (equal(h, h)).
        locks = [
            self._handle_lock(h)
            for h in dict.fromkeys(sorted(handles, key=repr))
        ]
        for lk in locks:
            lk.acquire()
        try:
            return self._exec(op)
        finally:
            for lk in reversed(locks):
                lk.release()

    def _handle_lock(self, h: Any) -> threading.Lock:
        with self._meta:
            if h not in self._handles:
                raise KeyError(f"no such handle: {h!r}")
            return self._hlocks.setdefault(h, threading.Lock())

    def _grid_lock(self, g: Any) -> threading.Lock:
        with self._meta:
            if g not in self._grids:
                raise KeyError(f"no such grid: {g!r}")
            return self._glocks.setdefault(g, threading.Lock())

    def _replace_grid(self, gname: Any, grid: "_Grid") -> None:
        """Install/replace a grid under its object lock. Swapping without
        the lock would let a concurrent in-flight grid_apply's
        acknowledged write vanish silently; the lock entry is created
        unconditionally because a not-yet-existing name can be racing a
        grid_new + apply. Shared by grid_new and grid_from_binary so the
        replace discipline cannot drift between the two paths."""
        with self._meta:
            lk = self._glocks.setdefault(gname, threading.Lock())
        with lk:
            with self._meta:
                self._grids[gname] = grid

    def _insert_handle(self, name: str, state: Any) -> int:
        """Allocate id and insert in one _meta section: every mutation of
        the handle map goes through _meta (or holds the handle's own lock,
        for update's write-back), keeping _handle_lock's membership check
        race-free even without the GIL."""
        with self._meta:
            self._next += 1
            h = self._next
            self._handles[h] = (name, state)
            return h

    def _state(self, handle: Any) -> Tuple[str, Any]:
        if handle not in self._handles:
            raise KeyError(f"no such handle: {handle!r}")
        return self._handles[handle]

    def _exec(self, op: Any) -> Any:
        tag = str(op[0])
        if tag == "new":
            _, type_atom, args = op
            name = str(type_atom)
            crdt = registry.scalar(name)
            return self._insert_handle(name, crdt.new(*_from_term(args)))
        if tag == "from_binary":
            _, type_atom, blob = op
            name = str(type_atom)
            return self._insert_handle(name, wire.from_reference_binary(name, blob))
        if tag == "downstream":
            _, h, op_term, dc, ts = op
            name, state = self._state(h)
            crdt = registry.scalar(name)
            ctx = _FixedCtx(dc_id=_from_term(dc), ts=int(ts))
            eff = crdt.downstream(op_from_term(op_term), state, ctx)
            return op_to_term(eff)
        if tag == "update":
            _, h, eff_term = op
            name, state = self._state(h)
            crdt = registry.scalar(name)
            state, extras = crdt.update(op_from_term(eff_term), state)
            self._handles[h] = (name, state)
            return [op_to_term(e) for e in extras]
        if tag == "batch_merge":
            # {batch_merge, Type, [Handle | StateBinary, ...]} -> new handle
            # holding the join of all inputs (the north-star entry point:
            # N replica states merged in one batched device pass).
            _, type_atom, items = op
            name = str(type_atom)
            states = []
            for it in items:
                if isinstance(it, (bytes, bytearray)):
                    states.append(wire.from_reference_binary(name, it))
                else:
                    item_name, st = self._state(it)
                    if item_name != name:
                        raise ValueError(
                            f"handle {it!r} holds {item_name!r}, not {name!r}"
                        )
                    states.append(st)
            from ..core.batch_merge import batch_merge

            return self._insert_handle(name, batch_merge(name, states))
        if tag == "is_type":
            # Registry predicates (antidote_ccrdt.erl:61-65), so a BEAM
            # host can interrogate the library without local knowledge.
            return registry.is_type(str(op[1]))
        if tag == "generates_extra_operations":
            return registry.generates_extra_operations(str(op[1]))
        if tag == "is_operation":
            _, type_atom, op_term = op
            crdt = registry.scalar(str(type_atom))
            return bool(crdt.is_operation(op_from_term(op_term)))
        if tag == "require_state_downstream":
            _, type_atom, op_term = op
            crdt = registry.scalar(str(type_atom))
            return bool(crdt.require_state_downstream(op_from_term(op_term)))
        if tag == "is_replicate_tagged":
            _, type_atom, eff_term = op
            crdt = registry.scalar(str(type_atom))
            return bool(crdt.is_replicate_tagged(op_from_term(eff_term)))
        if tag == "value":
            _, h = op
            name, state = self._state(h)
            return _to_term(registry.scalar(name).value(state))
        if tag == "to_binary":
            _, h = op
            name, state = self._state(h)
            return wire.to_reference_binary(name, state)
        if tag == "equal":
            _, h1, h2 = op
            n1, s1 = self._state(h1)
            n2, s2 = self._state(h2)
            return n1 == n2 and registry.scalar(n1).equal(s1, s2)
        if tag == "compact":
            _, h, effects = op
            name, _ = self._state(h)
            crdt = registry.scalar(name)
            log = [op_from_term(e) for e in effects]
            changed = True
            while changed:
                changed = False
                for i in range(len(log)):
                    if log[i] is None:
                        continue
                    for j in range(i + 1, len(log)):
                        if log[j] is None:
                            continue
                        if crdt.can_compact(log[i], log[j]):
                            log[i], log[j] = crdt.compact_ops(log[i], log[j])
                            changed = True
                            break
                    if changed:
                        break
            return [op_to_term(e) for e in log if e is not None]
        if tag == "grid_compact":
            # Whole-log compaction of a host effect-op log in one
            # vectorized pass (ops/compaction.py) — the device-path
            # equivalent of the scalar pairwise `compact` op above (the
            # reference's can_compact/2 + compact_ops/2 walk,
            # antidote_ccrdt.erl:55-56). Same effect-term shapes in and
            # out; m_keep (proplist) optionally bounds surviving adds per
            # id for topk_rmv (default: keep all, reference semantics).
            _, type_atom, params, effects = op
            from ..ops.compaction import compact_effect_ops

            m_keep = None
            for kv in params:
                if (isinstance(kv, tuple) and len(kv) == 2
                        and str(kv[0]) == "m_keep"):
                    m_keep = int(kv[1])
            log = [op_from_term(e) for e in effects]
            out = compact_effect_ops(str(type_atom), log, m_keep=m_keep)
            return [op_to_term(e) for e in out]
        if tag == "free":
            _, h = op
            with self._meta:
                self._handles.pop(h, None)
                self._hlocks.pop(h, None)
            return True
        if tag == "grid_new":
            _, gname, type_atom, params = op
            grid = _Grid(str(type_atom), params)  # built outside _meta
            self._replace_grid(gname, grid)
            return True
        if tag == "grid_apply":
            _, gname, per_replica = op
            return self._grids[gname].apply(per_replica)
        if tag == "grid_apply_extras":
            _, gname, per_replica = op
            return self._grids[gname].apply_extras(per_replica)
        if tag == "grid_apply_packed":
            _, gname, groups = op
            return self._grids[gname].apply_packed(groups)
        if tag == "grid_apply_packed_multi":
            _, gname, batches = op
            return self._grids[gname].apply_packed_multi(batches)
        if tag == "grid_apply_extras_packed":
            _, gname, groups = op
            return self._grids[gname].apply_extras_packed(groups)
        if tag == "grid_merge_all":
            _, gname = op
            self._grids[gname].merge_all()
            return True
        if tag == "grid_observe":
            _, gname, replica, key = op
            return self._grids[gname].observe(int(replica), int(key))
        if tag == "grid_to_binary":
            _, gname = op
            return self._grids[gname].to_binary()
        if tag == "grid_from_binary":
            _, gname, blob = op
            grid = _Grid.from_binary(blob)  # built outside _meta
            self._replace_grid(gname, grid)
            return True
        if tag == "metrics":
            # {metrics} -> OpenMetrics exposition text (binary). In-band
            # scrape over the same listener the data plane uses, so a
            # BEAM host (or Prometheus via a tiny shim) can inspect a
            # live worker without a side channel. Reads a snapshot, so a
            # scrape can never corrupt the registry.
            from ..obs import export as obs_export

            self.metrics.count("bridge.scrapes")
            return obs_export.prometheus_text(self.metrics).encode("utf-8")
        if tag == "query":
            # {query, Payload} -> serve-plane response bytes, verbatim.
            # Same canonical request/response codec as the tcp frame and
            # POST /query, so host-language clients get byte-identical
            # answers on every surface — including an rtrace "trace"
            # context in the request and the "rtrace" echo in the
            # response, which this op carries opaquely like any other
            # payload byte.
            handler = self.query_handler
            if handler is None:
                raise ValueError("no serve plane installed")
            self.metrics.count("bridge.queries")
            return bytes(handler(bytes(op[1])))
        if tag == "write":
            # {write, Payload} -> ingest-plane ack bytes, verbatim. Same
            # canonical codec as the tcp {write} frame and POST /write,
            # so host-language writers get byte-identical acks — and the
            # same tiered durability contract — on every surface.
            handler = self.write_handler
            if handler is None:
                raise ValueError("no ingest plane installed")
            self.metrics.count("bridge.writes")
            return bytes(handler(bytes(op[1])))
        raise ValueError(f"unknown op: {tag}")


class _FixedCtx:
    """ReplicaContext stand-in with caller-provided (dc, ts) — over the
    bridge the host supplies both, mirroring how Antidote owns the clock
    (topk_rmv.erl:104-105)."""

    def __init__(self, dc_id, ts: int):
        self.dc_id = dc_id
        self._ts = ts

    def stamp(self):
        return (self.dc_id, self._ts)
