"""Bridge server: a persistent CCRDT worker a BEAM-shaped host can drive.

Stands in for the reference's host integration surface (the Antidote side
of the behaviour contract, SURVEY.md §1): a threaded TCP server speaking
`{packet, 4}` + ETF (see `protocol`), holding

* **scalar instances** — handle -> (type, state); the full callback
  surface (downstream/update/value/compact/to_binary/...) over the wire,
  states interchangeable with reference `term_to_binary` snapshots; and
* **dense grids** — named [n_replicas, n_keys] dense states on the JAX
  backend (TPU when available); op batches are packed to the dense op
  structs, applied in one dispatch, and replicas fold with the lattice
  merge — the north-star `batch_merge` exposed to a host.

Concurrency: one OS thread per connection, per-OBJECT locking (round-2;
round 1 had one global lock, so a ~60ms dense grid dispatch stalled every
other client):

* every scalar handle and every grid has its own lock, created lazily;
* ops touching several handles (equal, batch_merge) acquire their locks
  in sorted order (no deadlock);
* a short meta lock guards only the handle/grid maps, lock tables and id
  allocation, and is never held while waiting on an object lock;
* registry predicates are pure reads and run lock-free.

Scalar states are copy-on-write (every `update` builds a new value), so
holding an object lock only for the duration of the op keeps readers of
old state references safe. A long grid dispatch therefore blocks ONLY
callers of that same grid — pinned by
`tests/test_bridge.py::test_long_grid_op_does_not_block_scalar_ops`.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core import wire
from ..core.behaviour import registry
from ..core.etf import Atom
from . import protocol as P


# Term <-> op conversion lives in protocol.py (shared with the client).
from .protocol import op_from_term, op_to_term, py_to_term, term_to_py

_from_term = term_to_py
_to_term = py_to_term


# --- dense grids ----------------------------------------------------------


class _Grid:
    """A named dense topk_rmv grid on the JAX backend."""

    def __init__(self, type_name: str, params: Dict[Any, Any]):
        def geti(key, default):
            return int(params.get(Atom(key), default))

        self.type_name = type_name
        self.R = geti("n_replicas", 2)
        self.NK = geti("n_keys", 1)
        # Resolved geometry (defaults applied) — embedded in snapshots so
        # grid_from_binary is self-contained.
        self.geometry = {
            "n_replicas": self.R,
            "n_keys": self.NK,
            "n_ids": geti("n_ids", 1024),
            "n_dcs": geti("n_dcs", self.R),
            "size": geti("size", 100),
            "slots_per_id": geti("slots_per_id", 4),
        }
        # Constructed through the registry's dense-factory surface — the
        # same path any embedder uses; only the op packing below is
        # topk_rmv-specific.
        self.dense = registry.make_dense(
            type_name,
            n_ids=self.geometry["n_ids"],
            n_dcs=self.geometry["n_dcs"],
            size=self.geometry["size"],
            slots_per_id=self.geometry["slots_per_id"],
        )
        self.state = self.dense.init(n_replicas=self.R, n_keys=self.NK)

    def to_binary(self) -> bytes:
        """Self-contained snapshot: (geometry map, dense-state blob) as an
        ETF term — a restarted worker (or another site) rebuilds the grid
        from the blob alone."""
        from ..core import etf, serial

        geom = {Atom(k): v for k, v in self.geometry.items()}
        return etf.encode(
            (geom, serial.dumps_dense(self.type_name, self.state))
        )

    @classmethod
    def from_binary(cls, blob: bytes) -> "_Grid":
        import jax

        from ..core import etf, serial

        term = etf.decode(blob)
        if not (isinstance(term, tuple) and len(term) == 2):
            raise ValueError("grid snapshot must be a (geometry, state) pair")
        geom, state_blob = term
        grid = cls("topk_rmv", dict(geom))
        name, state = serial.loads_dense(state_blob, grid.state)
        if name != grid.type_name:
            # A different dense type's blob can be treedef-compatible yet
            # carry foreign merge semantics — reject, don't misinterpret.
            raise ValueError(
                f"snapshot holds dense type {name!r}, not {grid.type_name!r}"
            )
        for got, like in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(grid.state)
        ):
            if got.shape != like.shape:
                raise ValueError(
                    f"snapshot leaf shape {got.shape} != geometry {like.shape}"
                )
        grid.state = state
        return grid

    def apply(self, per_replica_ops) -> int:
        import jax.numpy as jnp

        from ..models.topk_rmv_dense import TopkRmvOps

        if len(per_replica_ops) != self.R:
            raise ValueError(f"expected {self.R} replica op lists")
        D = self.dense.D
        for ops in per_replica_ops:
            for op in ops:
                if op[0] not in (Atom("add"), Atom("rmv")):
                    raise ValueError(f"unknown grid op tag: {op[0]!r}")
        adds = [[op for op in ops if op[0] == Atom("add")] for ops in per_replica_ops]
        rmvs = [[op for op in ops if op[0] == Atom("rmv")] for ops in per_replica_ops]
        B = max(1, max(len(a) for a in adds))
        Br = max(1, max(len(r) for r in rmvs))
        a = np.zeros((self.R, B, 5), np.int32)  # key,id,score,dc,ts (ts=0 pad)
        r_key = np.zeros((self.R, Br), np.int32)
        r_id = np.full((self.R, Br), -1, np.int32)
        r_vc = np.zeros((self.R, Br, D), np.int32)
        I, NK = self.dense.I, self.NK
        for ri, ops in enumerate(adds):
            for j, (_, key, id_, score, dc, ts) in enumerate(ops):
                if not 0 <= dc < D:
                    # An out-of-range add dc would create an element no
                    # tombstone can ever dominate (the filter's select-scan
                    # never matches it) — reject rather than immortalize.
                    raise ValueError(f"dc {dc} out of range")
                if not (0 <= key < NK and 0 <= id_ < I):
                    # The dense kernels index with clamping gathers /
                    # mode='drop' scatters: an out-of-range id would read the
                    # wrong element's tombstones and then be silently
                    # discarded — reject at the boundary instead.
                    raise ValueError(f"add (key={key}, id={id_}) out of range")
                a[ri, j] = (key, id_, score, dc, ts)
        for ri, ops in enumerate(rmvs):
            for j, (_, key, id_, vc_list) in enumerate(ops):
                if not (0 <= key < NK and 0 <= id_ < I):
                    raise ValueError(f"rmv (key={key}, id={id_}) out of range")
                r_key[ri, j] = key
                r_id[ri, j] = id_
                for dc, ts in vc_list:
                    if not 0 <= dc < D:
                        raise ValueError(f"dc {dc} out of range")
                    r_vc[ri, j, dc] = ts
        ops_batch = TopkRmvOps(
            add_key=jnp.asarray(a[:, :, 0]),
            add_id=jnp.asarray(a[:, :, 1]),
            add_score=jnp.asarray(a[:, :, 2]),
            add_dc=jnp.asarray(a[:, :, 3]),
            add_ts=jnp.asarray(a[:, :, 4]),
            rmv_key=jnp.asarray(r_key),
            rmv_id=jnp.asarray(r_id),
            rmv_vc=jnp.asarray(r_vc),
        )
        self.state, extras = self.dense.apply_ops(self.state, ops_batch)
        return int(np.asarray(extras.dominated).sum())

    def merge_all(self) -> None:
        """Fold all replica rows with the lattice join and broadcast the
        result back — the one-dispatch inter-DC reconciliation."""
        import jax
        import jax.numpy as jnp

        state = self.state
        r = self.R
        while r > 1:
            half = r // 2
            top = jax.tree.map(lambda x: x[:half], state)
            bot = jax.tree.map(lambda x: x[half : 2 * half], state)
            merged = self.dense.merge(top, bot)
            if r % 2:
                odd = jax.tree.map(lambda x: x[2 * half : r], state)
                merged = jax.tree.map(
                    lambda m, o: jnp.concatenate([m, o], axis=0), merged, odd
                )
            state = merged
            r = half + (r % 2)
        self.state = jax.tree.map(
            lambda x: jnp.broadcast_to(x[:1], (self.R,) + x.shape[1:]), state
        )

    def observe(self, replica: int, key: int):
        import jax

        if not (0 <= replica < self.R and 0 <= key < self.NK):
            raise ValueError(f"observe ({replica}, {key}) out of range")
        # Slice to the one requested cell before the observe sort — a full
        # dense.value() would sort and host-transfer the whole [R, NK] grid
        # (and hold the server lock while doing it).
        cell = jax.tree.map(lambda x: x[replica : replica + 1, key : key + 1], self.state)
        return [(_to_term(i), s) for (i, s) in self.dense.value(cell)[0][0]]


# --- server ---------------------------------------------------------------


class BridgeServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._handles: Dict[Any, Tuple[str, Any]] = {}
        self._grids: Dict[Any, _Grid] = {}
        self._next = 0
        # Lock order: object locks (handles/grids) outrank _meta; _meta is
        # only ever taken alone or inside an already-held object lock.
        self._meta = threading.Lock()
        self._hlocks: Dict[Any, threading.Lock] = {}
        self._glocks: Dict[Any, threading.Lock] = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                buf = bytearray()
                while True:
                    try:
                        chunk = self.request.recv(1 << 16)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    for term in P.unpack_frames(buf):
                        self.request.sendall(P.pack_frame(outer._dispatch(term)))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((host, port), Handler)
        self.address = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- dispatch ----------------------------------------------------------

    # Which operand positions hold handles that must be locked, per tag.
    _HANDLE_ARGS = {
        "downstream": (1,), "update": (1,), "value": (1,), "to_binary": (1,),
        "compact": (1,), "equal": (1, 2),
    }
    _GRID_TAGS = {"grid_apply", "grid_merge_all", "grid_observe", "grid_to_binary"}

    def _dispatch(self, term: Any) -> Any:
        if not (isinstance(term, tuple) and len(term) == 3 and term[0] == P.A_CALL):
            return P.reply_error(-1, f"bad request: {term!r}")
        _, req_id, op = term
        try:
            return P.reply_ok(req_id, self._exec_routed(op))
        except Exception as e:  # noqa: BLE001 - all errors go to the client
            return P.reply_error(req_id, f"{type(e).__name__}: {e}")

    def _exec_routed(self, op: Any) -> Any:
        """Acquire exactly the locks the op needs, then run it."""
        tag = str(op[0])
        if tag == "free":
            try:
                lk = self._handle_lock(op[1])
            except KeyError:
                return True  # already freed — free is idempotent
            with lk:
                return self._exec(op)
        if tag in self._HANDLE_ARGS:
            handles = [op[i] for i in self._HANDLE_ARGS[tag]]
        elif tag == "batch_merge":
            # Lock the handle items; inline binaries need no lock.
            handles = [it for it in op[2] if not isinstance(it, (bytes, bytearray))]
        elif tag in self._GRID_TAGS:
            with self._grid_lock(op[1]):
                return self._exec(op)
        else:
            # new / from_binary / grid_new create objects (inserted under
            # _meta inside _exec); registry predicates are pure reads.
            return self._exec(op)
        # repr-sort = one global acquisition order; dedup because an op may
        # name the same handle twice (equal(h, h)).
        locks = [
            self._handle_lock(h)
            for h in dict.fromkeys(sorted(handles, key=repr))
        ]
        for lk in locks:
            lk.acquire()
        try:
            return self._exec(op)
        finally:
            for lk in reversed(locks):
                lk.release()

    def _handle_lock(self, h: Any) -> threading.Lock:
        with self._meta:
            if h not in self._handles:
                raise KeyError(f"no such handle: {h!r}")
            return self._hlocks.setdefault(h, threading.Lock())

    def _grid_lock(self, g: Any) -> threading.Lock:
        with self._meta:
            if g not in self._grids:
                raise KeyError(f"no such grid: {g!r}")
            return self._glocks.setdefault(g, threading.Lock())

    def _insert_handle(self, name: str, state: Any) -> int:
        """Allocate id and insert in one _meta section: every mutation of
        the handle map goes through _meta (or holds the handle's own lock,
        for update's write-back), keeping _handle_lock's membership check
        race-free even without the GIL."""
        with self._meta:
            self._next += 1
            h = self._next
            self._handles[h] = (name, state)
            return h

    def _state(self, handle: Any) -> Tuple[str, Any]:
        if handle not in self._handles:
            raise KeyError(f"no such handle: {handle!r}")
        return self._handles[handle]

    def _exec(self, op: Any) -> Any:
        tag = str(op[0])
        if tag == "new":
            _, type_atom, args = op
            name = str(type_atom)
            crdt = registry.scalar(name)
            return self._insert_handle(name, crdt.new(*_from_term(args)))
        if tag == "from_binary":
            _, type_atom, blob = op
            name = str(type_atom)
            return self._insert_handle(name, wire.from_reference_binary(name, blob))
        if tag == "downstream":
            _, h, op_term, dc, ts = op
            name, state = self._state(h)
            crdt = registry.scalar(name)
            ctx = _FixedCtx(dc_id=_from_term(dc), ts=int(ts))
            eff = crdt.downstream(op_from_term(op_term), state, ctx)
            return op_to_term(eff)
        if tag == "update":
            _, h, eff_term = op
            name, state = self._state(h)
            crdt = registry.scalar(name)
            state, extras = crdt.update(op_from_term(eff_term), state)
            self._handles[h] = (name, state)
            return [op_to_term(e) for e in extras]
        if tag == "batch_merge":
            # {batch_merge, Type, [Handle | StateBinary, ...]} -> new handle
            # holding the join of all inputs (the north-star entry point:
            # N replica states merged in one batched device pass).
            _, type_atom, items = op
            name = str(type_atom)
            states = []
            for it in items:
                if isinstance(it, (bytes, bytearray)):
                    states.append(wire.from_reference_binary(name, it))
                else:
                    item_name, st = self._state(it)
                    if item_name != name:
                        raise ValueError(
                            f"handle {it!r} holds {item_name!r}, not {name!r}"
                        )
                    states.append(st)
            from ..core.batch_merge import batch_merge

            return self._insert_handle(name, batch_merge(name, states))
        if tag == "is_type":
            # Registry predicates (antidote_ccrdt.erl:61-65), so a BEAM
            # host can interrogate the library without local knowledge.
            return registry.is_type(str(op[1]))
        if tag == "generates_extra_operations":
            return registry.generates_extra_operations(str(op[1]))
        if tag == "is_operation":
            _, type_atom, op_term = op
            crdt = registry.scalar(str(type_atom))
            return bool(crdt.is_operation(op_from_term(op_term)))
        if tag == "require_state_downstream":
            _, type_atom, op_term = op
            crdt = registry.scalar(str(type_atom))
            return bool(crdt.require_state_downstream(op_from_term(op_term)))
        if tag == "is_replicate_tagged":
            _, type_atom, eff_term = op
            crdt = registry.scalar(str(type_atom))
            return bool(crdt.is_replicate_tagged(op_from_term(eff_term)))
        if tag == "value":
            _, h = op
            name, state = self._state(h)
            return _to_term(registry.scalar(name).value(state))
        if tag == "to_binary":
            _, h = op
            name, state = self._state(h)
            return wire.to_reference_binary(name, state)
        if tag == "equal":
            _, h1, h2 = op
            n1, s1 = self._state(h1)
            n2, s2 = self._state(h2)
            return n1 == n2 and registry.scalar(n1).equal(s1, s2)
        if tag == "compact":
            _, h, effects = op
            name, _ = self._state(h)
            crdt = registry.scalar(name)
            log = [op_from_term(e) for e in effects]
            changed = True
            while changed:
                changed = False
                for i in range(len(log)):
                    if log[i] is None:
                        continue
                    for j in range(i + 1, len(log)):
                        if log[j] is None:
                            continue
                        if crdt.can_compact(log[i], log[j]):
                            log[i], log[j] = crdt.compact_ops(log[i], log[j])
                            changed = True
                            break
                    if changed:
                        break
            return [op_to_term(e) for e in log if e is not None]
        if tag == "free":
            _, h = op
            with self._meta:
                self._handles.pop(h, None)
                self._hlocks.pop(h, None)
            return True
        if tag == "grid_new":
            _, gname, type_atom, params = op
            if str(type_atom) != "topk_rmv":
                raise ValueError("dense grids support topk_rmv")
            grid = _Grid(str(type_atom), params)  # built outside _meta
            with self._meta:
                self._grids[gname] = grid
            return True
        if tag == "grid_apply":
            _, gname, per_replica = op
            return self._grids[gname].apply(per_replica)
        if tag == "grid_merge_all":
            _, gname = op
            self._grids[gname].merge_all()
            return True
        if tag == "grid_observe":
            _, gname, replica, key = op
            return self._grids[gname].observe(int(replica), int(key))
        if tag == "grid_to_binary":
            _, gname = op
            return self._grids[gname].to_binary()
        if tag == "grid_from_binary":
            _, gname, blob = op
            grid = _Grid.from_binary(blob)  # built outside _meta
            # Replacing a grid must hold its object lock, or a concurrent
            # acknowledged grid_apply on the old object would vanish
            # silently. Create the lock entry unconditionally — a
            # not-yet-existing name can be racing a grid_new + apply.
            with self._meta:
                lk = self._glocks.setdefault(gname, threading.Lock())
            with lk:
                with self._meta:
                    self._grids[gname] = grid
            return True
        raise ValueError(f"unknown op: {tag}")


class _FixedCtx:
    """ReplicaContext stand-in with caller-provided (dc, ts) — over the
    bridge the host supplies both, mirroring how Antidote owns the clock
    (topk_rmv.erl:104-105)."""

    def __init__(self, dc_id, ts: int):
        self.dc_id = dc_id
        self._ts = ts

    def stamp(self):
        return (self.dc_id, self._ts)
