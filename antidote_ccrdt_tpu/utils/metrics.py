"""Metrics + profiling: the observability layer the reference lacks.

SURVEY.md §5 records the reference has no instrumentation at all; the
BASELINE metrics (merges/sec, p50 merge latency) therefore need first-class
counters here. Design: process-local, lock-free-enough registries of
counters and latency recorders, plus thin hooks into the JAX profiler for
TPU timeline traces.

Usage:

    m = Metrics()
    with m.timer("sync"):
        rp.sync()
    m.count("ops_applied", rp.ops_applied)
    m.summary()                       # {"sync": {"p50_ms": ...}, ...}

    with device_trace("apply_ops"):   # shows up in the TPU profiler timeline
        state, _ = D.apply_ops(state, ops)

    with profile("/tmp/trace"):       # full XLA/TPU trace for one region
        run_benchmark()
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


class LatencyRecorder:
    """Append-only duration series with percentile summaries."""

    __slots__ = ("samples",)

    def __init__(self) -> None:
        self.samples: List[float] = []

    def record(self, seconds: float) -> None:
        self.samples.append(seconds)

    def summary(self) -> Dict[str, float]:
        if not self.samples:
            return {"n": 0}
        a = np.asarray(self.samples)
        return {
            "n": int(a.size),
            "mean_ms": float(a.mean() * 1e3),
            "p50_ms": float(np.percentile(a, 50) * 1e3),
            "p90_ms": float(np.percentile(a, 90) * 1e3),
            "p99_ms": float(np.percentile(a, 99) * 1e3),
            "total_s": float(a.sum()),
        }


class Metrics:
    """Named counters + latency recorders. One instance per harness run.

    Counter updates are guarded by a lock: the net/ transports bump
    counters from sender/reader threads concurrently with the gossip
    loop, and an unguarded read-modify-write would silently drop counts
    (list.append in `timer` is atomic under the GIL; the += on a dict
    slot is not)."""

    def __init__(self) -> None:
        self.counters: Dict[str, float] = {}
        self.latencies: Dict[str, LatencyRecorder] = {}
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def count(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + delta

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self.counters[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one sample into a named distribution (same reservoirs
        the timers feed, so exporters/summary pick it up unchanged).
        For non-duration histograms like `wal.group_size`."""
        with self._lock:
            rec = self.latencies.setdefault(name, LatencyRecorder())
        rec.record(float(value))

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        # Recorder creation must hold the lock: two threads racing the
        # setdefault could each observe "missing", and the loser's
        # recorder (plus any samples already on it) would be dropped —
        # and an exporter iterating `latencies` mid-insert would see a
        # dict mutated during iteration. The `record` call itself stays
        # outside (list.append is atomic under the GIL).
        with self._lock:
            rec = self.latencies.setdefault(name, LatencyRecorder())
        t0 = time.perf_counter()
        try:
            yield
        finally:
            rec.record(time.perf_counter() - t0)

    def rate(self, counter: str, timer: Optional[str] = None) -> float:
        """counter / (timer's total seconds, or wall time since creation)."""
        n = self.counters.get(counter, 0.0)
        if timer is not None:
            total = sum(self.latencies[timer].samples) if timer in self.latencies else 0.0
        else:
            total = time.perf_counter() - self._t0
        return n / total if total > 0 else 0.0

    def snapshot(self) -> Dict[str, Any]:
        """Consistent point-in-time copy: counters and raw latency
        samples, taken under the lock. This is what exporters and the
        cross-process aggregation (obs/export.py, CCRDT_METRICS_DIR)
        read — never the live dicts, which sender/reader threads are
        still mutating. JSON-serializable as-is."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "latencies": {n: list(r.samples) for n, r in self.latencies.items()},
            }

    def merge(self, snap: Dict[str, Any]) -> None:
        """Fold another process's `snapshot()` into this registry:
        counters sum, latency samples concatenate. Used by drill
        supervisors to aggregate worker metrics dumps into one
        fleet-wide view."""
        with self._lock:
            for name, v in snap.get("counters", {}).items():
                self.counters[name] = self.counters.get(name, 0.0) + float(v)
            for name, samples in snap.get("latencies", {}).items():
                rec = self.latencies.setdefault(name, LatencyRecorder())
                rec.samples.extend(float(s) for s in samples)

    def summary(self) -> Dict[str, Any]:
        snap = self.snapshot()
        out: Dict[str, Any] = dict(snap["counters"])
        for name, samples in snap["latencies"].items():
            rec = LatencyRecorder()
            rec.samples = samples
            out[name] = rec.summary()
        return out


# --- JAX profiler hooks ---------------------------------------------------


@contextlib.contextmanager
def device_trace(name: str) -> Iterator[None]:
    """Annotate a region so it appears on the device timeline in profiler
    traces (no-op cost when no trace is being captured)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a full JAX/XLA profiler trace (TensorBoard format) for the
    enclosed region."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
