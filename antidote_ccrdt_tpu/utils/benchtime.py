"""Shared benchmark timing discipline for bench.py / benchmarks/*.

Centralizes the three measurement rules every benchmark in this repo must
follow (previously duplicated between bench.py and benchmarks/bench_all.py):

1. **Host-readback sync.** On tunneled TPU backends (axon)
   `jax.block_until_ready` returns while the device is still executing
   (measured), so every timed region must close with `sync()` — a real
   device->host transfer of one element.
2. **Scan-fused windows.** Per-dispatch tunnel overhead is 10-30ms; rounds
   are stacked into [W, ...] op pytrees and run as one `lax.scan` dispatch
   per window so the measurement is true device throughput.
3. **Distinct per-round batches.** Each round in a window gets freshly
   generated ops, defeating loop-invariant hoisting of the op upload.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence, Tuple

import numpy as np


def sync(x):
    """Force completion via host readback of one leaf element."""
    import jax

    return np.asarray(jax.tree.leaves(x)[0].ravel()[0])


def stack_rounds(batches: Sequence):
    """Stack per-round op pytrees into one [W, ...] window pytree."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda *xs: jnp.stack(xs), *batches)


def windowed(
    apply_fn: Callable,
    state,
    stacked_windows: Sequence,
    ops_per_round: int,
) -> Tuple[float, float]:
    """Time W-round scan-fused windows; returns (ops/sec, ms/round p50).

    `stacked_windows[0]` is the compile+warmup window and is not timed.
    Per-round latency is window_time / W — a smoothed estimator (individual
    rounds inside one dispatch cannot be timed without per-round syncs,
    which would measure tunnel RTT instead of compute).
    """
    import jax
    from jax import lax

    @jax.jit
    def run(state, stacked):
        def body(st, ops):
            return apply_fn(st, ops), ()

        out, _ = lax.scan(body, state, stacked)
        return out

    W = len(jax.tree.leaves(stacked_windows[0])[0])
    state = run(state, stacked_windows[0])  # compile + warm
    sync(state)
    times = []
    for stacked in stacked_windows[1:]:
        t0 = time.perf_counter()
        state = run(state, stacked)
        sync(state)
        times.append((time.perf_counter() - t0) / W)
    per_round = float(np.percentile(times, 50))
    total_ops = ops_per_round * W * len(times)
    return total_ops / (sum(times) * W), per_round * 1e3
