"""Version-compat shims for the JAX surface this repo spans.

The package is written against the current JAX spelling (top-level
`jax.shard_map`, `check_vma=` keyword); pinned CI images ship 0.4.x
where the same primitive lives in `jax.experimental.shard_map` and the
replication check is spelled `check_rep`. Call sites import `shard_map`
from here and always use the new spelling — the wrapper translates when
running on an old release.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # JAX >= 0.6

    _NEW_API = True
except ImportError:  # 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEW_API = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        kw["check_vma" if _NEW_API else "check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
