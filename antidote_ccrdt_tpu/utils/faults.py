"""Deterministic, seeded fault injection for the whole op path.

The elastic tier's correctness story is "everything is a join, so any
failure is just a retry" — but the seed repo only ever *simulated*
failures inside `net.sim`. This registry lets the REAL code paths fail:
named injection points are compiled into the production modules
(`net.transport.FsTransport`, `net.tcp._PeerLink`, `bridge.client
.BridgeClient`, `harness.checkpoint`, `harness.wal`) and stay dormant
until a plan is installed. The canonical points:

    transport.publish        FsTransport snapshot write
    transport.publish_delta  FsTransport delta write
    transport.fetch_delta    FsTransport delta read
    tcp.send                 _PeerLink frame send
    bridge.read              BridgeClient reply read
    wal.fsync                WriteAheadLog record fsync
    ckpt.replace             checkpoint/WAL atomic-replace commit
    pager.hydrate            out-of-core partition page-in (core/pager.py)
    router.route             fleet-router per-attempt routing decision
                             (serve/router.py: drop == connection loss,
                             raise == attempt failure, delay == stall)

(Any other dotted name works — the registry is generic; these are the
wired ones.)

Design constraints, in order:

* **Zero cost when disabled.** Call sites guard with the module-level
  ``if faults.ACTIVE:`` bool — one global load on the hot path, no
  function call, no dict lookup. `install` flips it.
* **Deterministic and replayable.** Every point owns a counter of hits
  and an RNG seeded from (plan seed, point name) only — independent of
  wall clock, PIDs, or interleaving of OTHER points. A spec fires at
  explicit hit indices (``at``) and/or with probability ``rate`` drawn
  from that per-point RNG; the decision sequence for a point is a pure
  function of (seed, its own hit ordinal), so a re-run with the same
  seed and the same per-point traffic replays the same schedule. The
  registry records a bounded trace of fired actions for assertions.
* **Crash-shaped actions.** ``raise`` throws OSError (the shape real
  infrastructure failures take: fsync EIO, ECONNRESET, torn NFS);
  ``truncate`` hands the call site a prefix of its payload (a torn
  write/read); ``delay`` sleeps (a stalled disk or peer); ``drop``
  tells the call site to silently skip the operation (a lost frame).

Call-site contract:

    if faults.ACTIVE:
        faults.fire("tcp.send")          # may raise / sleep; "drop" -> skip
    ...
    if faults.ACTIVE:
        blob = faults.mangle("transport.publish", blob)
        if blob is None:                  # dropped
            return

Subprocess drills opt in via the ``CCRDT_FAULTS`` env var (a JSON plan,
see `install_from_env`), so a supervisor can inject the same seeded
schedule into every worker it spawns.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..obs import events as obs_events

# The one-global-load hot-path gate. True iff a plan is installed.
ACTIVE = False

_ACTIONS = ("raise", "truncate", "delay", "drop")
_TRACE_MAX = 4096

ENV_VAR = "CCRDT_FAULTS"


class InjectedFault(OSError):
    """The OSError subclass injected `raise` actions throw — call sites
    treat it exactly like a real OSError (that is the point), tests can
    still tell it apart from an accidental genuine failure."""


class FaultSpec:
    """One rule at one point.

    action   one of raise | truncate | delay | drop
    at       explicit hit ordinals (0-based) this spec fires on
    rate     probability of firing on any hit (drawn from the point RNG;
             evaluated after `at`); 0 disables the probabilistic path
    max_fires  cap on total fires (None = unbounded)
    delay_s  sleep duration for `delay`
    keep     bytes kept by `truncate`: int >= 0 (prefix length) or a
             float in (0, 1) (fraction of the payload, floor)
    message  text for the injected OSError
    """

    __slots__ = (
        "action", "at", "rate", "max_fires", "delay_s", "keep", "message",
        "fires",
    )

    def __init__(
        self,
        action: str,
        at: Optional[List[int]] = None,
        rate: float = 0.0,
        max_fires: Optional[int] = None,
        delay_s: float = 0.0,
        keep: Any = 0,
        message: str = "injected fault",
    ):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} (use {_ACTIONS})")
        self.action = action
        self.at = frozenset(at or ())
        self.rate = float(rate)
        self.max_fires = max_fires
        self.delay_s = float(delay_s)
        self.keep = keep
        self.message = message
        self.fires = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultSpec":
        return cls(
            action=d["action"],
            at=list(d.get("at", ())),
            rate=float(d.get("rate", 0.0)),
            max_fires=d.get("max_fires"),
            delay_s=float(d.get("delay_s", 0.0)),
            keep=d.get("keep", 0),
            message=d.get("message", "injected fault"),
        )


class _Point:
    """Per-point state: hit counter, its own RNG, its specs."""

    __slots__ = ("name", "specs", "rng", "hits")

    def __init__(self, name: str, specs: List[FaultSpec], seed: int):
        self.name = name
        self.specs = specs
        # Seed from (plan seed, point name) ONLY: a point's schedule must
        # not depend on how often other points were hit. zlib.crc32 is
        # stable across processes (unlike hash()).
        self.rng = random.Random((seed << 32) ^ zlib.crc32(name.encode()))
        self.hits = 0


class _Registry:
    def __init__(self, plan: Dict[str, List[FaultSpec]], seed: int):
        self.seed = seed
        self.points = {n: _Point(n, specs, seed) for n, specs in plan.items()}
        self.trace: List[Tuple[str, int, str]] = []  # (point, hit, action)
        self.lock = threading.Lock()

    def decide(self, name: str) -> Optional[FaultSpec]:
        """Advance the point's hit counter and pick the firing spec (or
        None). One RNG draw per rate-bearing spec per hit, fired or not —
        the decision sequence is a pure function of the hit ordinal."""
        pt = self.points.get(name)
        if pt is None:
            return None
        with self.lock:
            hit = pt.hits
            pt.hits += 1
            chosen: Optional[FaultSpec] = None
            for spec in pt.specs:
                fires = hit in spec.at
                if spec.rate > 0.0:
                    draw = pt.rng.random()
                    fires = fires or draw < spec.rate
                if fires and (
                    spec.max_fires is None or spec.fires < spec.max_fires
                ):
                    if chosen is None:  # first matching spec wins; later
                        chosen = spec   # rate draws still consumed above
            if chosen is not None:
                chosen.fires += 1
                if len(self.trace) < _TRACE_MAX:
                    self.trace.append((name, hit, chosen.action))
                # Flight-record every firing: seeing *which* injected
                # fault preceded a failure is the whole point of pairing
                # the chaos plan with the obs plane.
                obs_events.emit(
                    "fault.hit", point=name, hit=hit, action=chosen.action
                )
            return chosen


_registry: Optional[_Registry] = None
_install_lock = threading.Lock()


def install(plan: Dict[str, Any], seed: int = 0) -> None:
    """Install a fault plan: {point: [FaultSpec | dict, ...]}. Replaces
    any existing plan. Flips the hot-path gate on."""
    global _registry, ACTIVE
    norm: Dict[str, List[FaultSpec]] = {}
    for name, specs in plan.items():
        norm[name] = [
            s if isinstance(s, FaultSpec) else FaultSpec.from_dict(s)
            for s in specs
        ]
    with _install_lock:
        _registry = _Registry(norm, seed)
        ACTIVE = True


def uninstall() -> None:
    global _registry, ACTIVE
    with _install_lock:
        ACTIVE = False
        _registry = None


class injected:
    """Context manager for tests: install on enter, uninstall on exit."""

    def __init__(self, plan: Dict[str, Any], seed: int = 0):
        self.plan, self.seed = plan, seed

    def __enter__(self):
        install(self.plan, seed=self.seed)
        return self

    def __exit__(self, *exc):
        uninstall()
        return False


def install_from_env(env: Optional[Dict[str, str]] = None) -> bool:
    """Install the plan in ``CCRDT_FAULTS`` (JSON: {"seed": int,
    "points": {point: [spec-dict, ...]}}), if set. Returns whether a
    plan was installed — drills call this once at startup so a
    supervisor controls the whole fleet's schedule."""
    raw = (env if env is not None else os.environ).get(ENV_VAR)
    if not raw:
        return False
    cfg = json.loads(raw)
    install(cfg.get("points", {}), seed=int(cfg.get("seed", 0)))
    return True


def plan_to_env(points: Dict[str, List[Dict[str, Any]]], seed: int = 0) -> str:
    """The env-var payload for `install_from_env` (dict specs only —
    JSON round-trip)."""
    return json.dumps({"seed": seed, "points": points})


# -- call-site surface -----------------------------------------------------


def fire(point: str) -> str:
    """Evaluate `point` for this hit. Returns the action taken: "ok"
    (nothing fired), "drop" (caller must skip the operation), or "delay"
    (the sleep already happened). `raise` actions raise InjectedFault.
    `truncate` at a payload-less site degrades to "ok" — use `mangle`
    where there are bytes to tear."""
    reg = _registry
    if reg is None:
        return "ok"
    spec = reg.decide(point)
    if spec is None:
        return "ok"
    if spec.action == "raise":
        raise InjectedFault(f"{point}: {spec.message}")
    if spec.action == "delay":
        time.sleep(spec.delay_s)
        return "delay"
    if spec.action == "drop":
        return "drop"
    return "ok"  # truncate without a payload


def mangle(point: str, data: bytes) -> Optional[bytes]:
    """Evaluate `point` against a byte payload. Returns the (possibly
    torn) payload, or None when the operation must be dropped entirely.
    raise/delay behave as in `fire`."""
    reg = _registry
    if reg is None:
        return data
    spec = reg.decide(point)
    if spec is None:
        return data
    if spec.action == "raise":
        raise InjectedFault(f"{point}: {spec.message}")
    if spec.action == "delay":
        time.sleep(spec.delay_s)
        return data
    if spec.action == "drop":
        return None
    # truncate
    keep = spec.keep
    if isinstance(keep, float):
        keep = int(len(data) * keep)
    return data[: max(0, int(keep))]


# -- introspection (tests / drills) ----------------------------------------


def trace() -> List[Tuple[str, int, str]]:
    """Bounded log of (point, hit ordinal, action) for every fire so
    far — the replay-determinism assertion surface."""
    reg = _registry
    return list(reg.trace) if reg is not None else []


def hits(point: str) -> int:
    reg = _registry
    if reg is None or point not in reg.points:
        return 0
    return reg.points[point].hits
