"""Jit-boundary shape/dtype validation and silent-drop observability.

SURVEY.md §5's race-detection row: the BEAM reference needs no sanitizers
because every callback is a pure Erlang function; the dense engine's
analog is keeping kernels pure and *checking structure at the jit
boundary*, where host data (wire input, checkpoint restores, generated op
batches) becomes device arrays. Two failure classes are covered:

* **Structural** (`check_state`, `check_ops`) — wrong dtype, wrong rank,
  mismatched batch axes, a rmv_vc whose DC width disagrees with the
  engine config. These raise immediately with a path-qualified message;
  under jit they are trace-time checks and cost nothing at runtime.
* **Semantic drops** (`topk_rmv_drop_report`) — the kernels deliberately
  drop out-of-range/padding ops (convergence-safe, see
  `TopkRmvDense._apply_one_replica`), which is correct but silent. The
  report counts per-field violations in one tiny jitted reduction so
  harnesses/bridges can distinguish "all padding" from "a feed is
  emitting garbage" and alarm on the latter (wire it to
  `utils.metrics.Metrics.count`).

The scalar engines need none of this: they validate per-op in Python
(`is_operation`, explicit ValueError on malformed effects).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp


def _leaves_with_paths(tree: Any):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield jax.tree_util.keystr(path), leaf


def check_tree_dtype(tree: Any, what: str, dtype=jnp.int32) -> None:
    """Every array leaf of `tree` must have exactly `dtype` (bool leaves
    are allowed — they are masks, not payloads)."""
    for path, leaf in _leaves_with_paths(tree):
        got = jnp.asarray(leaf).dtype
        if got == jnp.bool_:
            continue
        if got != dtype:
            raise TypeError(
                f"{what}{path}: dtype {got}, expected {jnp.dtype(dtype).name} "
                f"(host ints silently upcast to i64 break jit caches and "
                f"double HBM traffic)"
            )


def check_state(dense: Any, state: Any) -> None:
    """Structural check of a dense state against its engine config.

    Validates dtype, the shared [R, NK] leading batch axes, and the
    config-derived trailing dims (I/M/D for topk_rmv-shaped states) by
    comparing against a freshly built reference structure — so it works
    for every registered dense engine without per-type code. Use after
    checkpoint restore or any host-side state surgery."""
    check_tree_dtype(state, type(state).__name__)
    leaves = jax.tree_util.tree_leaves(state)
    if not leaves:
        raise ValueError("empty state pytree")
    lead = jnp.asarray(leaves[0]).shape[:2]
    if len(lead) < 2:
        raise ValueError(
            f"state leaves must carry [n_replicas, n_keys, ...] batch axes; "
            f"got shape {jnp.asarray(leaves[0]).shape}"
        )
    # eval_shape: the reference structure without allocating it — at
    # production capacities a real init would transiently double state
    # memory right when a large checkpoint is being restored. R/NK must
    # stay static (init builds shape tuples from them), hence the closure.
    ref = jax.eval_shape(lambda: dense.init(lead[0], lead[1]))
    got_paths = dict(_leaves_with_paths(state))
    for path, ref_leaf in _leaves_with_paths(ref):
        if path not in got_paths:
            raise ValueError(f"state is missing leaf {path}")
        got_shape = jnp.asarray(got_paths[path]).shape
        if got_shape != ref_leaf.shape:
            raise ValueError(
                f"state{path}: shape {got_shape}, engine config expects "
                f"{ref_leaf.shape}"
            )


def check_ops(state_or_replicas: Any, ops: Any, dense: Any = None) -> None:
    """Structural check of an op batch: i32 leaves, a consistent leading
    replica axis matching the state's, and — when the engine is passed —
    config-derived trailing dims (a rmv_vc whose DC width disagrees with
    the engine's D would otherwise fail deep inside the tombstone matmul
    with an opaque shape error)."""
    check_tree_dtype(ops, type(ops).__name__)
    if dataclasses.is_dataclass(state_or_replicas):
        n_replicas = jax.tree_util.tree_leaves(state_or_replicas)[0].shape[0]
    else:
        n_replicas = int(state_or_replicas)
    for path, leaf in _leaves_with_paths(ops):
        shape = jnp.asarray(leaf).shape
        if not shape or shape[0] != n_replicas:
            raise ValueError(
                f"ops{path}: leading axis {shape[:1] or '()'} != n_replicas "
                f"{n_replicas}"
            )
    if dense is not None and hasattr(ops, "rmv_vc"):
        got_d = jnp.asarray(ops.rmv_vc).shape[-1]
        if got_d != dense.D:
            raise ValueError(
                f"ops.rmv_vc DC width {got_d} != engine n_dcs {dense.D}"
            )


@functools.lru_cache(maxsize=64)
def _drop_counts_fn(NK: int, I: int, D: int):
    """Cached-per-config jitted reduction (a fresh inner @jit would
    retrace and recompile on every report call)."""

    @jax.jit
    def counts(ops):
        add_pad = ops.add_ts <= 0
        bad_key = (ops.add_key < 0) | (ops.add_key >= NK)
        bad_id = (ops.add_id < 0) | (ops.add_id >= I)
        bad_dc = (ops.add_dc < 0) | (ops.add_dc >= D)
        add_bad = ~add_pad & (bad_key | bad_id | bad_dc)
        rmv_pad = ops.rmv_id < 0
        rmv_bad = ~rmv_pad & (
            (ops.rmv_key < 0) | (ops.rmv_key >= NK) | (ops.rmv_id >= I)
        )
        return (
            jnp.sum(add_pad), jnp.sum(add_bad),
            jnp.sum(~add_pad & bad_key), jnp.sum(~add_pad & bad_id),
            jnp.sum(~add_pad & bad_dc),
            jnp.sum(rmv_pad), jnp.sum(rmv_bad),
        )

    return counts


def topk_rmv_drop_report(dense: Any, state: Any, ops: Any) -> Dict[str, int]:
    """Count ops the kernels will drop, by reason, in one device reduction.

    Padding conventions (add_ts <= 0, rmv_id < 0) are counted separately
    from genuine range violations, so a monitor can alert on the latter
    while ignoring the former. Returns plain ints (host-synced)."""
    NK = jax.tree_util.tree_leaves(state)[0].shape[1]
    counts = _drop_counts_fn(NK, dense.I, dense.D)
    (a_pad, a_bad, a_key, a_id, a_dc, r_pad, r_bad) = counts(ops)
    return {
        "add_padding": int(a_pad),
        "add_dropped_out_of_range": int(a_bad),
        "add_bad_key": int(a_key),
        "add_bad_id": int(a_id),
        "add_bad_dc": int(a_dc),
        "rmv_padding": int(r_pad),
        "rmv_dropped_out_of_range": int(r_bad),
    }
