"""Per-type batched query kernels: one dispatch materializes every key.

A snapshot answers three query shapes —

    value   the type's full observable for one key
    topk    the first k entries of a ranked observable
    range   entries whose score falls in [lo, hi] (leaderboard windows)

— and the kernel strategy is the same for every engine: fold the
snapshot's replica rows to the single read-side row with the engine's
own merge lattice (log2(R) batched dispatches through
`harness.dense_replay.fold_rows`; `MonoidLift.total` for lifted MONOID
engines, whose read-side reconciliation is the + fold, not the
version-pick join), run the engine's jitted `observe` ONCE over the
whole key axis, and pull the result to the host. That single
materialization answers arbitrarily many queries: per-query work is a
numpy gather over the key axis, and a batch of identical hot queries
collapses to one gather (`answer` memoizes within the batch; the
cross-batch memo is `serve.cache.HotKeyCache`).

Bit-identity contract (tests/test_serve_staleness.py): the "value"
answer for key k equals the engine's own `value()` of the folded
snapshot at that key — for score-table engines (`topk_rmv`, `topk`,
`leaderboard`) it IS `dense.value(folded)[0][k]` reshaped to JSON
(tuples become 2-lists), for scalar observables (lifted average) the
observed float, for vocab tables (lifted wordcount) the nonzero
(token_index, count) pairs in index order.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import devprof, profile


class SnapshotView:
    """Host-side materialization of one snapshot: everything query
    answering needs, in numpy. `mode` picks the per-type answer shape:

      table    per-key ranked [(id, score), ...] lists (JOIN score tables)
      scalar   one number per key (lifted average)
      vocab    a [NK, V] count table (lifted wordcount)
    """

    __slots__ = ("mode", "table", "arr", "n_keys")

    def __init__(self, mode: str, table=None, arr=None, n_keys: int = 0):
        self.mode = mode
        self.table = table  # mode "table": list of per-key [(id, score)] lists
        self.arr = arr      # mode "scalar"/"vocab": np.ndarray [NK] / [NK, V]
        self.n_keys = int(n_keys)


def materialize(dense: Any, state: Any) -> SnapshotView:
    """Fold replica rows, observe once, pull to host — the one device
    round-trip a snapshot ever pays, regardless of query volume."""
    import jax

    from ..harness.dense_replay import fold_rows

    if hasattr(dense, "total"):
        # MonoidLift: the read-side reconciliation is the inner + fold
        # (the lifted join would version-pick rows, which is the GOSSIP
        # lattice, not the read value).
        folded = dense.total(state)
        eng = dense.inner
    else:
        rows = int(jax.tree.leaves(state)[0].shape[0])
        folded = fold_rows(dense, state, range(rows)) if rows > 1 else state
        eng = dense

    if hasattr(eng, "value"):
        # Score-table engines: value() is the reference observable —
        # per-key ranked (id, score) lists, already host-materialized.
        # The device dispatch inside is the engine's jitted observe,
        # whose cache the observatory watches.
        if profile.ACTIVE or devprof.ACTIVE:
            with profile.dispatch(
                "serve.materialize",
                fn=getattr(eng, "observe", None),
                operands=(folded,),
            ):
                table = eng.value(folded)[0]
        else:
            table = eng.value(folded)[0]
        return SnapshotView("table", table=table, n_keys=len(table))

    if profile.ACTIVE or devprof.ACTIVE:
        with profile.dispatch(
            "serve.materialize",
            fn=getattr(eng, "observe", None),
            operands=(folded,),
        ):
            obs = eng.observe(folded)
    else:
        obs = eng.observe(folded)
    obs = np.asarray(jax.device_get(obs))[0]  # drop row axis
    if obs.ndim <= 1:
        arr = obs.reshape(-1)
        return SnapshotView("scalar", arr=arr, n_keys=arr.shape[0])
    return SnapshotView("vocab", arr=obs, n_keys=obs.shape[0])


def query_key(q: Dict[str, Any]) -> Tuple:
    """Canonical identity of one query — the batch-memo and hot-key
    cache key. Unknown fields are deliberately excluded: two requests
    asking the same question share one computed answer."""
    return (
        str(q.get("op", "value")),
        int(q.get("key", 0)),
        None if q.get("k") is None else int(q["k"]),
        None if q.get("lo") is None else int(q["lo"]),
        None if q.get("hi") is None else int(q["hi"]),
    )


def _pairs(entries) -> List[List[int]]:
    return [[int(i), int(s)] for i, s in entries]


def answer_one(view: SnapshotView, q: Dict[str, Any]) -> Any:
    """One query against one materialized view. Returns the JSON-shaped
    value, or raises ValueError for a malformed query (the plane turns
    that into a per-result error, never a dropped batch)."""
    op, key, k, lo, hi = query_key(q)
    if not (0 <= key < view.n_keys):
        raise ValueError(f"key {key} out of range [0, {view.n_keys})")
    if view.mode == "table":
        row = view.table[key]
        if op == "value":
            return _pairs(row)
        if op == "topk":
            return _pairs(row[: (len(row) if k is None else max(0, k))])
        if op == "range":
            lo_v = -math.inf if lo is None else lo
            hi_v = math.inf if hi is None else hi
            return _pairs(p for p in row if lo_v <= p[1] <= hi_v)
        raise ValueError(f"unknown op {op!r}")
    if view.mode == "scalar":
        if op != "value":
            raise ValueError(f"op {op!r} unsupported for scalar observables")
        return float(view.arr[key])
    # vocab: [V] counts for this key; entries are (token_index, count).
    counts = view.arr[key]
    nz = np.flatnonzero(counts)
    if op == "value":
        return [[int(v), int(counts[v])] for v in nz]
    if op == "topk":
        ranked = sorted(nz, key=lambda v: (-int(counts[v]), int(v)))
        return [[int(v), int(counts[v])] for v in ranked[: (len(ranked) if k is None else max(0, k))]]
    if op == "range":
        lo_v = -math.inf if lo is None else lo
        hi_v = math.inf if hi is None else hi
        return [[int(v), int(counts[v])] for v in nz if lo_v <= int(counts[v]) <= hi_v]
    raise ValueError(f"unknown op {op!r}")


def answer(view: SnapshotView, queries: List[Dict[str, Any]]) -> List[Any]:
    """Answer a batch against one view, memoizing identical queries —
    a thousand requests for the same hot leaderboard cost one gather."""
    memo: Dict[Tuple, Any] = {}
    out: List[Any] = []
    for q in queries:
        kq = query_key(q)
        if kq not in memo:
            memo[kq] = answer_one(view, q)
        out.append(memo[kq])
    return out
