"""Routing policy shared by the fleet READ tier (`serve.router`) and the
fleet WRITE tier (`serve.ingest`).

Both tiers walk the same HRW candidate list (`topo.anchor.
rendezvous_order` — fleet-wide agreement with no coordination, and the
write tier's "partition owner" is by construction the head of the same
list every read-tier client prefers), skip SWIM-dead peers, and guard
every peer behind the same circuit breaker. PR 14's review semantics are
load-bearing and live here exactly once:

* `CircuitBreaker.would_allow()` is the READ-ONLY eligibility check the
  candidate filter uses; `allow()` RESERVES the single half-open probe
  and must be called only when an attempt actually launches.
* Every launched attempt must resolve its breaker — `record_success`,
  `record_failure`, or `release_probe` for cancelled/abandoned attempts
  — or the probe slot leaks and the peer is excluded from routing
  forever (the PR 14 review bug).

`serve.router` re-exports `CircuitBreaker` and the state constants, so
existing imports (tests, dashboards) keep working.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..topo.anchor import rendezvous_order

# Breaker states (exported for tests / the dashboard).
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-peer closed -> open -> half-open breaker on *consecutive*
    failures. Clock-injectable so tests drive transitions on a fake
    clock; thread-safe because hedged attempts record from worker
    threads."""

    def __init__(
        self,
        fail_threshold: int = 3,
        cooldown_s: float = 2.0,
        mono: Callable[[], float] = time.monotonic,
    ):
        self.fail_threshold = max(1, int(fail_threshold))
        self.cooldown_s = float(cooldown_s)
        self.mono = mono
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consec_failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == OPEN and (
                self.mono() - self._opened_at >= self.cooldown_s
            ):
                return HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """May an attempt go to this peer now? While open: no. After the
        cooldown: exactly ONE in-flight probe (half-open) until it
        reports success or failure — or explicitly releases the slot.
        RESERVES the probe slot: call only when the attempt actually
        launches; eligibility filtering must use `would_allow()`."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.mono() - self._opened_at < self.cooldown_s:
                    return False
                self._state = HALF_OPEN
            if self._probing:
                return False
            self._probing = True
            return True

    def would_allow(self) -> bool:
        """Read-only eligibility: the same verdict `allow()` would give,
        without reserving the half-open probe slot. Candidate filters
        use this — a candidate that is listed but never actually tried
        must not consume (and leak) the probe."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and (
                self.mono() - self._opened_at < self.cooldown_s
            ):
                return False
            return not self._probing

    def release_probe(self) -> None:
        """Give back a reserved half-open probe without a verdict — for
        attempts that were cancelled or abandoned (a hedge loser reaped
        undone at the deadline, a discarded answer from a SWIM-dead
        peer). Without this the slot would leak and exclude the peer
        from routing forever."""
        with self._lock:
            self._probing = False

    def record_success(self) -> bool:
        """Returns True iff this success CLOSED a non-closed breaker."""
        with self._lock:
            closed_now = self._state != CLOSED
            self._state = CLOSED
            self._consec_failures = 0
            self._probing = False
            return closed_now

    def record_failure(self) -> bool:
        """Returns True iff this failure OPENED the breaker (threshold
        crossed, or a half-open probe failed)."""
        with self._lock:
            self._consec_failures += 1
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._consec_failures >= self.fail_threshold
            ):
                self._state = OPEN
                self._opened_at = self.mono()
                self._probing = False
                return True
            if self._state == OPEN:
                # Failure while open (e.g. a stale in-flight attempt):
                # restart the cooldown, it is evidence the peer is still bad.
                self._opened_at = self.mono()
            return False


class BreakerBoard:
    """Lazily-populated per-peer breaker registry with shared policy
    knobs. Both tiers of one client process can share a board, so a
    peer that fails writes is also demoted for reads (and vice versa) —
    connection loss is connection loss, whichever plane observed it."""

    def __init__(
        self,
        fail_threshold: int = 3,
        cooldown_s: float = 2.0,
        mono: Callable[[], float] = time.monotonic,
    ):
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.mono = mono
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, peer: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(peer)
            if br is None:
                br = CircuitBreaker(
                    self.fail_threshold, self.cooldown_s, self.mono
                )
                self._breakers[peer] = br
            return br

    def states(self) -> Dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {p: br.state for p, br in items}


def candidate_order(
    key: str,
    peers: List[str],
    verdict_fn: Optional[Callable[[str], str]] = None,
    breakers: Optional[BreakerBoard] = None,
    staleness_fn: Optional[Callable[[str], float]] = None,
    stale_soft_s: float = -1.0,
) -> List[str]:
    """The shared candidate walk: HRW rendezvous order on `key` (the
    head is the partition owner), peers beyond `stale_soft_s` demoted to
    a second bucket (stable within each — the read tier's staleness
    demotion; the write tier passes no staleness_fn, owner affinity must
    not wobble with lag), SWIM-``dead`` peers dropped, and open-breaker
    peers filtered READ-ONLY via `would_allow()` (probe reservation is
    the launcher's job)."""
    ordered = rendezvous_order(key, [str(p) for p in peers])
    if staleness_fn is not None and stale_soft_s >= 0:
        ordered = sorted(
            ordered,
            key=lambda p: 1 if (staleness_fn(p) or 0.0) > stale_soft_s else 0,
        )  # stable: HRW order preserved within each bucket
    out: List[str] = []
    for p in ordered:
        if verdict_fn is not None and verdict_fn(p) == "dead":
            continue
        if breakers is not None and not breakers.get(p).would_allow():
            continue
        out.append(p)
    return out
