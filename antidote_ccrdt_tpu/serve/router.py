"""`FleetRouter`: staleness-aware fleet query routing with failover.

One worker's `ServePlane` answers queries; a FLEET of them needs a
client-side router that decides *which* replica answers and what happens
when that replica is slow, stale, overloaded, or dead mid-query. This
module is that router, deliberately transport-agnostic: the caller
injects ``query_fn(peer, payload, timeout_s, cancel) -> bytes`` (TCP
`net.tcp.query_peer`, the sim transport, or direct in-process dispatch
in benches) and the router owns only the *policy*:

* **Candidate order** is `topo.anchor.rendezvous_order(key, peers)` —
  the same HRW ranking the anchor election uses, so every client walks
  the same preference list for the same key (cache affinity) and a
  peer's death never reorders the survivors. Peers whose observed
  staleness exceeds ``stale_soft_s`` are demoted to a second bucket
  (stable within each bucket): prefer fresh replicas, but a stale one
  still beats an error.
* **Degradation ladder — hedge → retry → failover → shed.** A request
  that runs past the peer's learned p99 latency gets a *hedged* twin on
  the next candidate (first success wins; the loser is cancelled and
  billed `router.hedge_wasted`). A failed attempt fails over to the
  next HRW candidate (`router.failovers`); a fully failed pass retries
  after jittered exponential backoff (`router.retries`, bounded). Only
  when every candidate sheds does the router return the shed — honestly,
  with the largest `retry_after_ms` hint the fleet offered — rather
  than queueing the overload somewhere invisible.
* **Mid-query failover** is idempotent by construction: responses carry
  ``(value, as_of_seq, staleness_bound_s)``, so re-asking another
  replica can only re-answer, never double-apply. A SWIM ``dead``
  verdict (injected `verdict_fn`) observed while an attempt is in
  flight cancels it and reroutes immediately — the router does not wait
  out the timeout of a peer the membership layer already buried.
* **Per-peer circuit breakers**: consecutive failures open the breaker
  (candidates are skipped while open); after ``breaker_cooldown_s`` one
  half-open probe is allowed through and either closes it or re-opens.
* **Session guarantees**: a query may carry a `serve.session` token
  (``{origin: seq}`` floor). The router routes only to peers whose
  last-learned applied watermarks cover the token (unknown peers are
  tried optimistically — the serving plane re-checks and answers
  ``session_uncovered``, teaching the router that peer's watermarks).
  If no live peer can cover the token the router waits up to
  ``session_wait_s`` (`router.session_waits`) and then fails honestly
  with ``session_unsatisfiable`` + the exact per-origin gaps, never
  silently serving a token-violating answer. ``session_mode="ignore"``
  strips the token from the wire (while still flight-recording what the
  session *required*) — the deliberately-violating arm the audit layer
  (`obs.audit.certify_sessions`) must catch.

Every decision is metered (`router.*` counters below) into the shared
`Metrics` registry, so the counters ride all three scrape surfaces for
free, and `utils.faults` point ``router.route`` fires per attempt so
chaos drills can inject routing-layer drops/stalls/raises.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..obs import events as obs_events, rtrace
from ..utils import faults
from ..utils.metrics import Metrics
from .routing_common import (  # noqa: F401 — CircuitBreaker + states
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerBoard,
    CircuitBreaker,
    candidate_order,
)
from .session import ClientSession, gaps as session_gaps, session_doc


class _Attempt:
    """One in-flight query attempt on one peer, run on a worker thread
    so the router's main loop can watch verdicts / trigger hedges /
    enforce deadlines while the transport blocks."""

    __slots__ = ("peer", "cancel", "done", "result", "error", "t0")

    def __init__(self, peer: str):
        self.peer = peer
        self.cancel = threading.Event()
        self.done = threading.Event()
        self.result: Optional[bytes] = None
        self.error: Optional[BaseException] = None
        self.t0 = 0.0


class FleetRouter:
    """Client-side fleet query router (see module docstring).

    Parameters the policy hangs off:

    peers        iterable OR callable returning the current peer names
                 (callable = live view, e.g. SWIM alive set + self).
    query_fn     (peer, payload_bytes, timeout_s, cancel_event) -> bytes;
                 raises (TimeoutError / OSError / ...) on failure. MUST
                 eventually return or raise within ~timeout_s; `cancel`
                 being set asks it to abandon the attempt early.
    verdict_fn   peer -> "alive" | "suspect" | "dead" (SWIM `state_of`);
                 None = everyone alive. "dead" peers are skipped up
                 front AND reroute in-flight attempts.
    staleness_fn peer -> observed staleness seconds (fed from
                 `obs.lag.LagTracker.report`); peers beyond
                 `stale_soft_s` sort behind fresh ones.
    hedge_after_s  fixed hedge trigger; None = learned per-peer p99
                 (needs `hedge_min_samples` observations first, so cold
                 routers never hedge blindly).
    session_mode "enforce" (default) routes/verifies tokens;
                 "ignore" strips them from the wire while still
                 recording requirements — the audit layer's negative
                 control.
    """

    def __init__(
        self,
        peers: Any,
        query_fn: Callable[[str, bytes, float, threading.Event], bytes],
        member: str = "router",
        metrics: Optional[Metrics] = None,
        verdict_fn: Optional[Callable[[str], str]] = None,
        staleness_fn: Optional[Callable[[str], float]] = None,
        stale_soft_s: float = 1.0,
        timeout_s: float = 2.0,
        retries: int = 2,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 1.0,
        hedge: bool = True,
        hedge_after_s: Optional[float] = None,
        hedge_min_samples: int = 8,
        breaker_failures: int = 3,
        breaker_cooldown_s: float = 2.0,
        session_mode: str = "enforce",
        session_wait_s: float = 1.0,
        session_poll_s: float = 0.05,
        mono: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        poll_s: float = 0.005,
        seed: int = 0,
        breakers: Optional[BreakerBoard] = None,
    ):
        if session_mode not in ("enforce", "ignore"):
            raise ValueError("session_mode must be 'enforce' or 'ignore'")
        self._peers_src = peers
        self.query_fn = query_fn
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.verdict_fn = verdict_fn
        self.staleness_fn = staleness_fn
        self.stale_soft_s = float(stale_soft_s)
        self.timeout_s = float(timeout_s)
        self.retries = max(0, int(retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.hedge = bool(hedge)
        self.hedge_after_s = hedge_after_s
        self.hedge_min_samples = max(1, int(hedge_min_samples))
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.session_mode = session_mode
        self.session_wait_s = float(session_wait_s)
        self.session_poll_s = float(session_poll_s)
        self.mono = mono
        self.sleep = sleep
        self.poll_s = float(poll_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # Shared with the write tier when the caller passes one board:
        # a peer that fails writes is demoted for reads too.
        self._board = (
            breakers
            if breakers is not None
            else BreakerBoard(breaker_failures, breaker_cooldown_s, mono)
        )
        # peer -> last-learned applied watermarks {origin: seq}, taught
        # by every response (success OR session_uncovered rejection).
        self._peer_watermarks: Dict[str, Dict[str, int]] = {}
        # peer -> recent latency samples (seconds) for the p99 hedge
        # trigger; bounded so estimates track the peer's present.
        self._lat: Dict[str, deque] = {}

    # -- introspection -------------------------------------------------------

    def _peers(self) -> List[str]:
        src = self._peers_src
        out = src() if callable(src) else src
        return [str(p) for p in out]

    def breaker(self, peer: str) -> CircuitBreaker:
        return self._board.get(peer)

    def peer_watermarks(self, peer: str) -> Optional[Dict[str, int]]:
        with self._lock:
            wm = self._peer_watermarks.get(peer)
            return dict(wm) if wm is not None else None

    def _learn_watermarks(self, peer: str, wm: Any) -> None:
        if not isinstance(wm, dict):
            return
        try:
            clean = {str(o): int(s) for o, s in wm.items()}
        except (TypeError, ValueError):
            return
        with self._lock:
            # Pointwise max: watermarks only advance; a racing older
            # response must not regress what we know the peer covers.
            cur = self._peer_watermarks.setdefault(peer, {})
            for o, s in clean.items():
                if s > cur.get(o, -1):
                    cur[o] = s

    def status(self) -> Dict[str, Any]:
        """Dashboard feed: per-peer breaker state + learned watermark
        height, plus the counters the column group renders."""
        breakers = self._board.states()
        with self._lock:
            wms = {
                p: (max(wm.values()) if wm else -1)
                for p, wm in self._peer_watermarks.items()
            }
        snap = self.metrics.snapshot()["counters"]
        return {
            "breakers": breakers,
            "peer_wm_max": wms,
            "counters": {
                k: v for k, v in snap.items() if k.startswith("router.")
            },
        }

    # -- candidate selection -------------------------------------------------

    def route(
        self, key: str, token: Optional[Dict[str, int]] = None
    ) -> Tuple[List[str], bool]:
        """The eligible candidate list for `key`, in preference order,
        plus a flag: True iff peers were excluded ONLY by session
        coverage (so waiting could help). HRW order, fresh-staleness
        bucket first, dead peers and open breakers dropped — the shared
        walk (`routing_common.candidate_order`), then the read tier's
        session-coverage filter on top."""
        ordered = candidate_order(
            key,
            self._peers(),
            verdict_fn=self.verdict_fn,
            breakers=self._board,
            staleness_fn=self.staleness_fn,
            stale_soft_s=self.stale_soft_s if self.staleness_fn else -1.0,
        )
        out: List[str] = []
        session_starved = False
        enforce = token and self.session_mode == "enforce"
        for p in ordered:
            if enforce:
                wm = self.peer_watermarks(p)
                # Unknown peer: optimistic — the plane re-checks and a
                # session_uncovered reply teaches us its watermarks.
                if wm is not None and session_gaps(wm, token):
                    session_starved = True
                    continue
            out.append(p)
        return out, session_starved and not out

    # -- the query path ------------------------------------------------------

    def query(
        self,
        queries: List[Dict[str, Any]],
        key: Optional[str] = None,
        max_staleness_s: Optional[float] = None,
        session: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """Route one query batch. `key` picks the HRW affinity (defaults
        to the first query's key field); `session` is a ClientSession,
        SessionToken, or raw ``{origin: seq}`` dict. Returns the decoded
        response dict, augmented with ``"peer"`` (who answered). Never
        raises for routing-layer failures — errors come back as honest
        ``{"error": ...}`` documents (unavailable / overloaded /
        session_unsatisfiable), so callers cannot hang and cannot
        mistake a failure for a value."""
        t0 = self.mono()
        self.metrics.count("router.queries")
        sess = session if isinstance(session, ClientSession) else None
        token = session_doc(
            sess.requirement() if sess is not None else session
        ) or {}
        if key is None:
            key = str(queries[0].get("key", "")) if queries else ""
        tr = rtrace.begin("read", key, t0) if rtrace.ACTIVE else None
        doc: Dict[str, Any] = {"queries": list(queries)}
        if max_staleness_s is not None:
            doc["max_staleness_s"] = float(max_staleness_s)
        if token and self.session_mode == "enforce":
            doc["session"] = token
        if tr is not None:
            # Only head-sampled traces ride the wire (server echo cost
            # scales with the sample rate); the payload stays opaque to
            # every transport, so no frame format changes.
            w = tr.wire()
            if w:
                doc["trace"] = w
        payload = (
            json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n"
        ).encode("utf-8")

        last_err: Optional[str] = None
        shed_hint: Optional[int] = None
        all_sheds = True  # falsified by any non-shed failure
        session_wait_deadline: Optional[float] = None
        round_i = 0
        first_route = True
        while round_i <= self.retries:
            # The first route hop opens at t0 so request prep (token +
            # payload build) lands in the route bucket instead of
            # leaking out of attribution coverage.
            t_route = t0 if first_route else self.mono()
            first_route = False
            order, starved = self.route(key, token)
            if tr is not None:
                # The route decision IS evidence: candidate order plus
                # the breaker verdicts that shaped it (closed breakers
                # shape nothing, so only open/half-open ride along).
                tr.hop("route", t_route, self.mono(),
                       candidates=list(order), starved=bool(starved),
                       breakers={p: s for p, s
                                 in self._board.states().items()
                                 if s != "closed"})
            if not order:
                if starved:
                    # Every live peer is excluded only by session
                    # coverage: wait for replication to catch up rather
                    # than burning retry rounds.
                    now = self.mono()
                    if session_wait_deadline is None:
                        session_wait_deadline = now + self.session_wait_s
                        self.metrics.count("router.session_waits")
                    if now < session_wait_deadline:
                        self.sleep(self.session_poll_s)
                        if tr is not None:
                            tr.hop("backoff", now, self.mono(),
                                   reason="session_wait")
                        continue
                    return self._finish_error(
                        t0, "session_unsatisfiable",
                        {"gaps": self._session_gaps(token)},
                        counter="router.session_unsatisfiable", tr=tr,
                    )
                last_err = last_err or "no eligible peers"
                all_sheds = False
                round_i += 1
                self._backoff(round_i, tr)
                continue
            outcome = self._run_pass(order, payload, token, tr)
            kind, detail = outcome[0], outcome[1]
            if kind == "ok":
                resp, peer = detail
                return self._finish_ok(t0, resp, peer, sess, token, tr)
            if kind == "uncovered":
                # Every candidate refused on session coverage (and
                # taught us its watermarks): this is replication lag,
                # not failure — wait it out, don't burn retry rounds.
                now = self.mono()
                if session_wait_deadline is None:
                    session_wait_deadline = now + self.session_wait_s
                    self.metrics.count("router.session_waits")
                if now >= session_wait_deadline:
                    return self._finish_error(
                        t0, "session_unsatisfiable",
                        {"gaps": self._session_gaps(token)},
                        counter="router.session_unsatisfiable", tr=tr,
                    )
                self.sleep(self.session_poll_s)
                if tr is not None:
                    tr.hop("backoff", now, self.mono(),
                           reason="session_wait")
                continue
            if kind == "shed":
                shed_hint = max(shed_hint or 0, int(detail or 0))
                last_err = "overloaded"
            else:
                all_sheds = False
                last_err = str(detail)
            round_i += 1
            if round_i <= self.retries:
                self.metrics.count("router.retries")
                self._backoff(round_i, tr)
        if shed_hint is not None and all_sheds:
            self.metrics.count("router.shed_returns")
            return self._finish_error(
                t0, "overloaded", {"retry_after_ms": shed_hint}, tr=tr,
            )
        return self._finish_error(
            t0, "unavailable", {"detail": last_err},
            counter="router.exhausted", tr=tr,
        )

    # -- one pass over the candidate list ------------------------------------

    def _run_pass(
        self, order: List[str], payload: bytes, token: Dict[str, int],
        tr: Optional[rtrace.Trace] = None,
    ) -> Tuple[str, Any]:
        """Walk `order` once. Returns ("ok", (resp, peer)) on success;
        ("uncovered", detail) when EVERY outcome was a session-coverage
        refusal (waiting can help); ("shed", retry_after_ms) when at
        least one peer shed and no one answered; ("err", detail)
        otherwise."""
        shed_hint: Optional[int] = None
        saw_shed = False
        saw_err = False
        saw_uncovered = False
        last_detail: Any = "no candidates"
        idx = 0
        while idx < len(order):
            peer = order[idx]
            if faults.ACTIVE:
                try:
                    if faults.fire("router.route") == "drop":
                        # Injected route loss == connection loss: bill a
                        # failover and walk on.
                        raise ConnectionError("router.route: injected drop")
                except faults.InjectedFault as e:
                    self._fail(peer, e)
                    last_detail = str(e)
                    saw_err = True
                    idx += 1
                    if idx < len(order):
                        self.metrics.count("router.failovers")
                    continue
                except ConnectionError as e:
                    self._fail(peer, e)
                    last_detail = str(e)
                    saw_err = True
                    idx += 1
                    if idx < len(order):
                        self.metrics.count("router.failovers")
                    continue
            hedge_peer = order[idx + 1] if idx + 1 < len(order) else None
            verdict, detail = self._attempt(peer, hedge_peer, payload, tr)
            if verdict == "ok":
                resp, who, a0, a1 = detail
                kind, fine = self._classify(who, resp, token, tr, a0, a1)
                if kind == "ok":
                    return ("ok", (fine, who))
                if kind == "shed":
                    saw_shed = True
                    shed_hint = max(shed_hint or 0, int(fine or 0))
                    last_detail = "overloaded"
                elif kind == "uncovered":
                    saw_uncovered = True
                    last_detail = fine
                else:
                    saw_err = True
                    last_detail = fine
                idx += 1
                if idx < len(order):
                    self.metrics.count("router.failovers")
                continue
            if verdict == "hedge_ok":
                # The hedge (order[idx+1]) answered; classify under ITS name.
                resp, who, a0, a1 = detail
                kind, fine = self._classify(who, resp, token, tr, a0, a1)
                if kind == "ok":
                    return ("ok", (fine, who))
                if kind == "shed":
                    saw_shed = True
                    shed_hint = max(shed_hint or 0, int(fine or 0))
                elif kind == "uncovered":
                    saw_uncovered = True
                    last_detail = fine
                else:
                    saw_err = True
                    last_detail = fine
                idx += 2  # both primary and hedge are spent
                if idx < len(order):
                    self.metrics.count("router.failovers")
                continue
            # dead / timeout / error on every leg of the attempt
            saw_err = True
            last_detail = detail
            idx += 1
            if idx < len(order):
                self.metrics.count("router.failovers")
        if saw_uncovered and not saw_err and not saw_shed:
            return ("uncovered", last_detail)
        if saw_shed:
            return ("shed", shed_hint)
        return ("err", last_detail)

    def _attempt(
        self, peer: str, hedge_peer: Optional[str], payload: bytes,
        tr: Optional[rtrace.Trace] = None,
    ) -> Tuple[str, Any]:
        """One (possibly hedged) attempt. Returns
        ("ok", (raw, peer, t_send, t_recv)),
        ("hedge_ok", (raw, hedge_peer, t_send, t_recv)), or
        ("fail", detail). The main thread watches: completion, the
        peer's SWIM verdict (dead -> cancel + reroute), the hedge
        trigger, and the deadline."""
        t_entry = self.mono()
        self.metrics.count("router.attempts")
        primary = self._launch(peer, payload)
        # The attempt window opens at _attempt entry: breaker/thread
        # launch setup is attempt cost, and the waterfall's wire bucket
        # (attempt union minus server time) must account for it.
        primary.t0 = t_entry
        hedge: Optional[_Attempt] = None
        deadline = primary.t0 + self.timeout_s
        hedge_at = self._hedge_at(peer, primary.t0, hedge_peer)
        primary_dead = False
        while True:
            if primary.done.is_set() and (
                primary.error is None or hedge is None or hedge.done.is_set()
            ):
                break
            if hedge is not None and hedge.done.is_set() and (
                hedge.error is None or primary.done.is_set()
            ):
                break
            now = self.mono()
            if now >= deadline:
                break
            if (
                not primary_dead
                and not primary.done.is_set()
                and self.verdict_fn is not None
                and self.verdict_fn(peer) == "dead"
            ):
                # SWIM buried the peer mid-query: stop waiting for it.
                # One-shot (guarded by `primary_dead`): later poll ticks
                # must not re-bill the same death.
                primary_dead = True
                primary.cancel.set()
                self.metrics.count("router.dead_reroutes")
                if tr is not None:
                    tr.hop("dead_reroute", now, peer=peer)
                if hedge is None:
                    self._fail(peer, TimeoutError("peer died mid-query"))
                    if tr is not None:
                        tr.hop("attempt", primary.t0, now, peer=peer,
                               ok=False, err="dead mid-query")
                    return ("fail", f"{peer} dead mid-query")
                # A hedge is still running — let it finish out the deadline.
                hedge_at = None
                deadline = min(deadline, now + self.timeout_s)
            if primary_dead and hedge is not None and hedge.done.is_set():
                return self._settle(primary, hedge, peer, dead=True, tr=tr)
            if (
                hedge is None
                and hedge_at is not None
                and now >= hedge_at
                and not primary.done.is_set()
            ):
                self.metrics.count("router.hedges")
                if tr is not None:
                    tr.hop("hedge_launch", now, peer=hedge_peer,
                           primary=peer)
                hedge = self._launch(hedge_peer, payload)  # type: ignore[arg-type]
            self.sleep(self.poll_s)
        return self._settle(primary, hedge, peer, dead=primary_dead, tr=tr)

    def _settle(
        self,
        primary: _Attempt,
        hedge: Optional[_Attempt],
        peer: str,
        dead: bool = False,
        tr: Optional[rtrace.Trace] = None,
    ) -> Tuple[str, Any]:
        """Pick the winner, cancel the loser, bill the hedge. Every
        attempt that LAUNCHED resolves its breaker here — success,
        failure, or an explicit `release_probe` for cancelled/undone
        attempts — so a half-open probe reservation can never leak."""
        now = self.mono()
        p_ok = primary.done.is_set() and primary.error is None
        h_ok = (
            hedge is not None and hedge.done.is_set() and hedge.error is None
        )

        def _att_hop(att: _Attempt, ok: bool, **f: Any) -> None:
            if tr is not None:
                tr.hop("attempt", att.t0, now, peer=att.peer, ok=ok, **f)

        if p_ok and not dead:
            if hedge is not None:
                hedge.cancel.set()
                self.metrics.count("router.hedge_wasted")
                self._abandon(hedge)
                _att_hop(hedge, False, hedge=True, wasted=True)
            self._succeed(primary)
            _att_hop(primary, True)
            return ("ok", (primary.result, primary.peer, primary.t0, now))
        if h_ok:
            primary.cancel.set()
            if p_ok:
                # SWIM-dead primary raced an answer in anyway; we chose
                # the hedge, so give back any probe the primary held
                # rather than billing a failure for a discarded success.
                self.breaker(peer).release_probe()
                _att_hop(primary, False, discarded="dead")
            else:
                self._fail(peer, primary.error or TimeoutError(
                    "peer died mid-query" if dead else "hedged out"
                ))
                _att_hop(primary, False,
                         err="dead mid-query" if dead else "hedged out")
            self.metrics.count("router.hedge_wins")
            self._succeed(hedge)  # type: ignore[arg-type]
            _att_hop(hedge, True, hedge=True)  # type: ignore[arg-type]
            return ("hedge_ok",  # type: ignore[union-attr]
                    (hedge.result, hedge.peer, hedge.t0, now))
        # Nobody won: cancel stragglers, bill the failure(s).
        primary.cancel.set()
        if hedge is not None:
            hedge.cancel.set()
            self._abandon(hedge)
            _att_hop(hedge, False, hedge=True)
        if primary.done.is_set() and primary.error is not None:
            self._fail(peer, primary.error)
            _att_hop(primary, False, err=str(primary.error))
            return ("fail", f"{peer}: {primary.error}")
        if p_ok:
            # (dead=True) The primary answered but SWIM buried it and no
            # hedge won: discard the answer, give the probe slot back.
            self.breaker(peer).release_probe()
            _att_hop(primary, False, discarded="dead")
            return ("fail", f"{peer} dead mid-query")
        self.metrics.count("router.timeouts")
        self._fail(peer, TimeoutError("query deadline exceeded"))
        _att_hop(primary, False, err="timeout")
        return ("fail", f"{peer}: timeout after {self.timeout_s}s")

    def _abandon(self, att: _Attempt) -> None:
        """Resolve the breaker for a cancelled/discarded attempt: bill
        what actually happened, or — if it never finished — just release
        the half-open probe slot it may be holding."""
        if att.done.is_set():
            if att.error is None:
                self._succeed(att)
            else:
                self._fail(att.peer, att.error)
        else:
            self.breaker(att.peer).release_probe()

    def _launch(self, peer: str, payload: bytes) -> _Attempt:
        # Reserve the half-open probe slot (if any) only now, when the
        # attempt actually goes out — `route()` filtered read-only, so
        # listed-but-untried candidates never consume it. `_settle`
        # guarantees the reservation is resolved or released.
        self.breaker(peer).allow()
        att = _Attempt(peer)
        att.t0 = self.mono()

        def run() -> None:
            try:
                att.result = self.query_fn(
                    peer, payload, self.timeout_s, att.cancel
                )
            except BaseException as e:  # noqa: BLE001 — surfaced via att.error
                att.error = e
            finally:
                att.done.set()

        threading.Thread(
            target=run, name=f"router-q-{peer}", daemon=True
        ).start()
        return att

    # -- response classification --------------------------------------------

    def _classify(
        self, peer: str, raw: Optional[bytes], token: Dict[str, int],
        tr: Optional[rtrace.Trace] = None,
        t_send: Optional[float] = None, t_recv: Optional[float] = None,
    ) -> Tuple[str, Any]:
        """("ok", resp_dict) | ("shed", retry_after_ms) |
        ("uncovered", detail) | ("err", detail)."""
        try:
            resp = json.loads(bytes(raw or b"").decode("utf-8"))
        except Exception as e:  # noqa: BLE001 — garbage == peer failure
            self.metrics.count("router.errors")
            self._fail(peer, e)
            return ("err", f"{peer}: undecodable response: {e}")
        echo = resp.pop("rtrace", None) if isinstance(resp, dict) else None
        if tr is not None and isinstance(echo, dict) \
                and t_send is not None and t_recv is not None:
            # (attempt send, server mid, attempt recv) is an NTP
            # exchange: absorb feeds the plane's ClockSync too.
            tr.absorb_echo(echo, t_send, t_recv)
        self._learn_watermarks(peer, resp.get("watermarks"))
        if tr is not None and t_recv is not None:
            # Decode + verdict classification is routing-plane work;
            # recording it keeps sub-ms requests' coverage honest.
            tr.hop("route", t_recv, self.mono(), step="classify",
                   peer=peer)
        err = resp.get("error")
        if err is not None:
            err_s = str(err)
            if err_s.startswith("overloaded"):
                # Admission control, not peer sickness: no breaker hit.
                self.metrics.count("router.sheds")
                return ("shed", resp.get("retry_after_ms", 0))
            if err_s.startswith("session_uncovered"):
                # The plane refused to violate the token; its watermarks
                # (just learned) steer the next candidate choice.
                self.metrics.count("router.session_uncovered")
                return ("uncovered", f"{peer}: session_uncovered")
            self.metrics.count("router.errors")
            self._fail(peer, RuntimeError(err_s))
            return ("err", f"{peer}: {err_s}")
        return ("ok", resp)

    # -- success / failure bookkeeping ---------------------------------------

    def _succeed(self, att: _Attempt) -> None:
        dt = max(0.0, self.mono() - att.t0)
        with self._lock:
            lat = self._lat.setdefault(att.peer, deque(maxlen=64))
            lat.append(dt)
        if self.breaker(att.peer).record_success():
            self.metrics.count("router.breaker_closes")

    def _fail(self, peer: str, err: BaseException) -> None:
        if isinstance(err, TimeoutError) or "timed out" in str(err):
            self.metrics.count("router.peer_timeouts")
        if self.breaker(peer).record_failure():
            self.metrics.count("router.breaker_opens")

    def _hedge_at(
        self, peer: str, t0: float, hedge_peer: Optional[str]
    ) -> Optional[float]:
        if not self.hedge or hedge_peer is None:
            return None
        if self.hedge_after_s is not None:
            return t0 + max(0.0, float(self.hedge_after_s))
        with self._lock:
            lat = self._lat.get(peer)
            if lat is None or len(lat) < self.hedge_min_samples:
                return None
            xs = sorted(lat)
        p99 = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
        return t0 + p99

    def _backoff(
        self, round_i: int, tr: Optional[rtrace.Trace] = None
    ) -> None:
        base = min(
            self.backoff_max_s, self.backoff_base_s * (2 ** (round_i - 1))
        )
        a = self.mono()
        self.sleep(base * (0.5 + self._rng.random()))  # jitter in [0.5, 1.5)
        if tr is not None:
            tr.hop("backoff", a, self.mono(), round=round_i)

    def _session_gaps(self, token: Dict[str, int]) -> Dict[str, Any]:
        """Best-known per-origin (have, want) shortfall across peers —
        the honest detail on session_unsatisfiable."""
        best: Dict[str, int] = {}
        with self._lock:
            for wm in self._peer_watermarks.values():
                for o, s in wm.items():
                    if s > best.get(o, -1):
                        best[o] = s
        return {
            o: {"have": hv, "want": wt}
            for o, (hv, wt) in session_gaps(best, token).items()
        }

    # -- finishers -----------------------------------------------------------

    def _finish_ok(
        self,
        t0: float,
        resp: Dict[str, Any],
        peer: str,
        sess: Optional[ClientSession],
        token: Dict[str, int],
        tr: Optional[rtrace.Trace] = None,
    ) -> Dict[str, Any]:
        self.metrics.count("router.successes")
        dt = max(0.0, self.mono() - t0)
        self.metrics.merge({"latencies": {"router.read": [dt]}})
        rtrace.commit(tr, "ok", dt * 1e3)
        wm = resp.get("watermarks")
        if sess is not None and isinstance(wm, dict):
            # Flight-record the accepted read with the floor it HAD to
            # satisfy — certify_sessions replays exactly this feed. In
            # session_mode="ignore" the requirement was never sent, so a
            # watermark shortfall here is precisely the violation the
            # audit must catch.
            sess.note_read(
                resp.get("member", peer),
                {str(o): int(s) for o, s in wm.items()},
                required=token,
            )
        out = dict(resp)
        out["peer"] = peer
        return out

    def _finish_error(
        self,
        t0: float,
        error: str,
        extra: Dict[str, Any],
        counter: Optional[str] = None,
        tr: Optional[rtrace.Trace] = None,
    ) -> Dict[str, Any]:
        if counter:
            self.metrics.count(counter)
        dt = max(0.0, self.mono() - t0)
        self.metrics.merge({"latencies": {"router.read": [dt]}})
        obs_events.emit("router.give_up", error=error)
        if tr is not None:
            outcome = {
                "overloaded": "shed",
                "session_unsatisfiable": "uncovered",
            }.get(error, "failed")
            if outcome == "failed" \
                    and "timeout" in str(extra.get("detail", "")):
                outcome = "deadline"
            rtrace.commit(tr, outcome, dt * 1e3)
        out: Dict[str, Any] = {"error": error}
        out.update(extra)
        return out


def tcp_query_fn(
    addrs: Any, connect_timeout_s: float = 0.5
) -> Callable[[str, bytes, float, threading.Event], bytes]:
    """Adapter: a `query_fn` over `net.tcp.query_peer` given `addrs` —
    a dict (or callable returning one) of peer -> (host, port). Raises
    KeyError for unknown peers (the router treats it as a failure and
    fails over)."""
    from ..net.tcp import query_peer

    def fn(
        peer: str, payload: bytes, timeout_s: float, cancel: threading.Event
    ) -> bytes:
        table = addrs() if callable(addrs) else addrs
        addr = table[peer]
        _member, resp = query_peer(
            tuple(addr), payload, timeout=timeout_s, cancel=cancel,
            connect_timeout=connect_timeout_s,
        )
        return resp

    return fn
