"""Hot-key answer cache: computed answers survive snapshot swaps.

The cheap tier of the serving plane. A computed answer is immutable for
the snapshot it was computed at, so it stays servable AFTER the replica
swaps forward — at a staleness cost that grows with age. Each entry is
``(value, as_of_seq)``; the plane recomputes the entry's staleness
bound from its snapshot's swap pedigree at every serve, and the
`max_staleness` query knob decides whether the aged entry still
qualifies or the query falls through to the fresh replica (re-filling
the entry at the new seq).

Bounded two ways: LRU capacity (`serve.cache_evictions`), and a seq
horizon — the plane retains swap pedigree for only the last few seqs,
and `purge_below` drops entries whose pedigree is gone (an answer whose
staleness can no longer be bounded must not be served).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Optional, Tuple


class HotKeyCache:
    """LRU of canonical query answers tagged with their snapshot seq.

    Keys are `kernels.query_key` tuples; values are (answer, as_of_seq).
    Thread-safety is provided by the plane's batcher (single drainer at
    a time), so no lock here.
    """

    def __init__(self, cap: int = 1024, metrics: Any = None):
        self.cap = max(1, int(cap))
        self.metrics = metrics
        self._entries: "OrderedDict[Tuple, Tuple[Any, int]]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: Tuple) -> Optional[Tuple[Any, int]]:
        hit = self._entries.get(key)
        if hit is not None:
            self._entries.move_to_end(key)
        return hit

    def put(self, key: Tuple, value: Any, seq: int) -> None:
        self._entries[key] = (value, int(seq))
        self._entries.move_to_end(key)
        while len(self._entries) > self.cap:
            self._entries.popitem(last=False)
            if self.metrics is not None:
                self.metrics.count("serve.cache_evictions")

    def purge_below(self, min_seq: int) -> int:
        """Drop entries older than the plane's pedigree horizon; returns
        how many were dropped."""
        stale = [k for k, (_, s) in self._entries.items() if s < min_seq]
        for k in stale:
            del self._entries[k]
        return len(stale)
