"""`WriteSession`: the client-side staging + pre-wire batching half of
the fleet write tier.

Clients stage scalar effect ops per partition key; `flush()` compacts
each key's burst through `ops.compaction.compact_effect_ops` — the SAME
PR 15 coalescing kernels the workers run, firing BEFORE the wire as the
CRDT scaling survey prescribes (delta compression at the edge) — and
ships the survivors as ONE ``CCRF`` range frame through `WriteRouter`.
The frame's ``[lo, hi]`` names the span of RAW staged ops the shipped
batch covers, so the wire itself records the coalescing provenance
(``hi - lo + 1`` raw ops entered, ``len(ops)`` survived).

The session also closes read-your-writes across tiers: every ack feeds
`ClientSession.note_write`, so the SAME token the read tier already
enforces (`session_gaps` in `serve.router`) now covers the client's own
writes — write through one tier, read through the other, never see
time go backwards.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..net.transport import encode_range_frame
from ..obs import rtrace
from ..utils.metrics import Metrics
from .ingest import ACK_DURABLE, WriteRouter
from .plane import encode
from .session import ClientSession


def effect_to_wire(effect: Tuple[str, Any]) -> List[Any]:
    """Effect tuple -> JSON-able form. Tuples become lists; a topk_rmv
    rmv vector-clock's int dc keys become strings (JSON object keys).
    The shape survives a round-trip through `effect_from_wire`."""

    def conv(x: Any) -> Any:
        if isinstance(x, tuple):
            return [conv(v) for v in x]
        if isinstance(x, dict):
            return {str(k): conv(v) for k, v in x.items()}
        return x

    kind, payload = effect
    return [str(kind), conv(payload)]


def effect_from_wire(doc: Any) -> Tuple[str, Any]:
    """Inverse of `effect_to_wire`: lists back to tuples, numeric dict
    keys back to ints — the scalar effect shape `ops.reference` /
    `compact_effect_ops` and the dense models' op builders expect."""

    def conv(x: Any) -> Any:
        if isinstance(x, list):
            return tuple(conv(v) for v in x)
        if isinstance(x, dict):
            return {
                (int(k) if str(k).lstrip("-").isdigit() else k): conv(v)
                for k, v in x.items()
            }
        return x

    kind, payload = doc[0], doc[1]
    return (str(kind), conv(payload))


class WriteSession:
    """Per-client write front door: stage -> compact -> frame -> route.

    Staged effects accumulate per partition key and auto-flush at
    `batch_max`; an explicit `flush()` drains everything. Each key's
    flush is ONE router write (one wire frame, one write_id), so owner
    failover and client retries stay idempotent per burst. write_ids
    are ``{session_id}:{n}`` — stable across the retry storm inside one
    `WriteRouter.write` call by construction (the router reuses the id
    it was given)."""

    def __init__(
        self,
        router: WriteRouter,
        type_name: str,
        session: Optional[ClientSession] = None,
        session_id: str = "ws",
        batch_max: int = 64,
        ack: str = ACK_DURABLE,
        k: int = 2,
        m_keep: Optional[int] = None,
        metrics: Optional[Metrics] = None,
    ):
        self.router = router
        self.type_name = str(type_name)
        self.session = session if session is not None else ClientSession()
        self.session_id = str(session_id)
        self.batch_max = max(1, int(batch_max))
        self.ack = ack
        self.k = int(k)
        # topk_rmv: bound surviving adds per id to the dense model's
        # slots_per_id — the fold keeps only the top-M slots anyway, so
        # shipping more than M adds for one id is pure wire waste.
        self.m_keep = m_keep
        self.metrics = metrics if metrics is not None else Metrics()
        self._lock = threading.Lock()
        self._staged: Dict[str, List[Tuple[str, Any]]] = {}
        self._n_staged = 0
        self._wid_n = 0
        self.raw_ops = 0      # staged ops entering compaction
        self.shipped_ops = 0  # survivors that hit the wire

    # -- staging -------------------------------------------------------------

    def stage(
        self, key: str, effect: Tuple[str, Any]
    ) -> Optional[List[Dict[str, Any]]]:
        """Park one effect op for `key`. Returns flush results when the
        staging buffer crossed `batch_max` (auto-flush), else None."""
        with self._lock:
            self._staged.setdefault(str(key), []).append(effect)
            self._n_staged += 1
            full = self._n_staged >= self.batch_max
        self.metrics.count("write_session.staged_ops")
        if full:
            return self.flush()
        return None

    def pending(self) -> int:
        with self._lock:
            return self._n_staged

    # -- the burst -> wire path ----------------------------------------------

    def flush(self) -> List[Dict[str, Any]]:
        """Compact + ship every staged burst; one result doc per key
        (the router's ack or honest error — `flush` never raises and
        never silently drops: a failed burst comes back as its error
        doc and the caller decides whether to re-stage)."""
        with self._lock:
            staged, self._staged = self._staged, {}
            self._n_staged = 0
        results: List[Dict[str, Any]] = []
        for key, effects in staged.items():
            results.append(self._ship(key, effects))
        if staged:
            self.metrics.count("write_session.flushes")
        return results

    def _ship(self, key: str, effects: List[Tuple[str, Any]]) -> Dict[str, Any]:
        raw_n = len(effects)
        try:
            from ..ops.compaction import compact_effect_ops

            compacted = compact_effect_ops(
                self.type_name, effects, self.m_keep
            )
        except Exception:  # noqa: BLE001 — unknown type etc.: ship raw
            self.metrics.count("write_session.compact_fallbacks")
            compacted = list(effects)
        with self._lock:
            # Provenance counters advance under the SAME lock hold that
            # assigns the write_id and computes lo: concurrent flushes
            # (auto-flush racing an explicit flush()) get disjoint
            # [lo, hi] ranges and an exact coalesce_ratio.
            self.raw_ops += raw_n
            self.shipped_ops += len(compacted)
            self._wid_n += 1
            wid = f"{self.session_id}:{self._wid_n}"
            lo = self.raw_ops - raw_n
        wire_ops = [effect_to_wire(e) for e in compacted]
        doc = {
            "write_id": wid,
            "ops": wire_ops,
            "ack": self.ack,
            "type": self.type_name,
        }
        if self.ack == "replicated_to_k":
            doc["k"] = self.k
        # The trace context must ride INSIDE the CCRF frame (the plane
        # sees only the inner doc), so the session mints it here and
        # hands the Trace to the router for hop recording + commit.
        tr = rtrace.begin("write", key) if rtrace.ACTIVE else None
        if tr is not None:
            tr.t0 = self.router.mono()
            w = tr.wire()
            if w:
                doc["trace"] = w
        # The burst is ONE range frame: [lo, hi] spans the raw staged
        # ops this shipment covers — coalescing provenance on the wire.
        payload = encode_range_frame(lo, lo + raw_n - 1, encode(doc))
        out = self.router.write(
            wire_ops, key, ack=self.ack, k=self.k, session=self.session,
            write_id=wid, payload=payload, trace=tr,
        )
        if out.get("error") is not None:
            self.metrics.count("write_session.errors")
        else:
            self.metrics.count(f"write_session.acks.{out.get('level')}")
        out["key"] = key
        out["raw_ops"] = raw_n
        out["shipped_ops"] = len(compacted)
        return out

    # -- introspection -------------------------------------------------------

    def coalesce_ratio(self) -> float:
        """Raw staged ops per wire op — the client-edge twin of the
        worker-side ``coalesce_ratio`` bench metric."""
        return self.raw_ops / self.shipped_ops if self.shipped_ops else 1.0

    def status(self) -> Dict[str, Any]:
        return {
            "pending": self.pending(),
            "raw_ops": self.raw_ops,
            "shipped_ops": self.shipped_ops,
            "coalesce_ratio": round(self.coalesce_ratio(), 3),
            "counters": self.metrics.snapshot()["counters"],
        }
