"""Fleet write tier: `IngestPlane` (worker side) + `WriteRouter`
(client side) — the write-path twin of `serve.plane` + `serve.router`.

Reads got a fleet product in PR 14; writes still entered through
whichever worker a client happened to hold, with no routing, no
batching, no durability contract, and no backpressure. This module
closes that gap:

* **Owner routing.** Every update routes to the partition-owning
  worker: the head of `topo.anchor.rendezvous_order(key, peers)` — the
  SAME ranking the read router and the anchor election use, so the
  fleet agrees on the owner without coordination and a reader's HRW
  walk lands on the replica its own writes went to (cache affinity +
  read-your-writes in one move). SWIM-``dead`` verdicts and the shared
  circuit breakers (`serve.routing_common`) fail writes over to the
  next candidate. Delivery semantics are explicit, not wishful:
  redelivery to the SAME plane is exactly-once — every write carries a
  client `write_id` the plane tracks from enqueue (in-flight registry)
  through fold (drain-time ack cache), so a retry attaches to the
  original or re-acks its ``(origin, seq)``, never re-folds. Failover
  to a DIFFERENT member is **at-least-once**: if the dead owner
  actually folded before its ack was lost (slow drain, killed after
  apply — its delta gossips or its WAL recovers), the successor folds
  the batch again under its own ``(origin, seq)``. The registered CRDT
  types absorb that duplicate under join (stamped adds dedup on merge);
  every fold emits an ``ingest.fold`` flight event carrying its
  write_id, so `obs.audit.certify_writes` reports cross-member
  duplicate applications and, with ``strict_exactly_once=True``,
  convicts them for deployments whose op streams are not
  duplicate-tolerant.
* **Pre-wire batching.** `WriteSession` (serve/write_session.py)
  compacts a staged burst through `ops.compaction.compact_effect_ops`
  and ships it as ONE `net.transport` ``CCRF`` range frame — the PR 15
  coalescing kernels firing BEFORE the wire, on the client, as the CRDT
  scaling survey frames delta compression at the edge.
* **Tiered durable acks.** ``applied`` = folded into the owner's
  in-memory state; ``durable`` = pinned to the PR 11
  ``wal.durable_seq`` watermark (the plane WAITS for the fsync
  watermark to pass the write's step before claiming it); and
  ``replicated_to_k`` = confirmed applied by k distinct members, which
  the ROUTER certifies by probing the replicas themselves (the owner
  cannot honestly attest what its peers hold). A level that cannot be
  reached inside the ack timeout is reported as the level actually
  achieved — never upgraded, so an ack is a contract, not a hope.
  ``ack_before_fsync=True`` deliberately breaks that contract (acks
  ``durable`` without waiting) — the violating arm
  `obs.audit.certify_writes` must convict.
* **Admission control.** The bounded ingest queue plus caller-injected
  pressure probes (WAL durability lag, overlap-queue depth, pager
  pressure) shed writers with an honest ``retry_after_ms`` derived from
  the observed drain rate — the write-side mirror of the read tier's
  `serve.queue_shed`, instead of queueing the overload invisibly.

Writes ride new ``{write}``/``{write_ack}`` frames on `net.tcp` +
`net.sim`, the bridge ``{write}`` op, and ``POST /write`` — the same
canonical JSON codec as the read tier, byte-identical on every surface.
The `utils.faults` point ``router.write`` fires per client attempt
(drop == connection loss, bills the breaker) and ``serve.write`` per
plane dispatch, so chaos drills can cut the write path at both ends.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..net.transport import FRAME_MAGIC, decode_range_frame
from ..obs import devprof, events as obs_events, rtrace
from ..utils import faults
from ..utils.metrics import Metrics
from .plane import encode
from .routing_common import BreakerBoard, candidate_order
from .session import ClientSession

ACK_APPLIED = "applied"
ACK_DURABLE = "durable"
ACK_REPLICATED = "replicated_to_k"
_ACK_LEVELS = (ACK_APPLIED, ACK_DURABLE, ACK_REPLICATED)

# Idempotency window: acks remembered per write_id (insertion-ordered
# eviction). Sized like the sim transport's cancelled-qid window — a
# retry storm dedups, a week-long drill cannot leak memory.
_ACK_CACHE_MAX = 4096


class _PendingWrite:
    """One write parked between the transport thread that received it
    and the round loop that folds it at the next step boundary.

    `t_stage` / `t_fold` / `kernel_ms` are rtrace stage marks on the
    plane's monotonic clock (stage = parked, fold = drained+applied);
    they ride the response echo only when the request carried a trace
    context."""

    __slots__ = (
        "ops", "write_id", "done", "seq", "error",
        "t_stage", "t_fold", "kernel_ms",
    )

    def __init__(self, ops: List[Any], write_id: Optional[str]):
        self.ops = ops
        self.write_id = write_id
        self.done = threading.Event()
        self.seq = -1
        self.error: Optional[str] = None
        self.t_stage = 0.0
        self.t_fold = 0.0
        self.kernel_ms = 0.0


class IngestPlane:
    """Worker-side write front door. Transport threads `handle()` raw
    ``{write}`` payloads; the worker's round loop `drain()`s the queue
    at each step boundary, folding every parked write into the live
    state so a write's ``seq`` IS the step whose WAL record and gossip
    delta carry it — durability and replication watermarks come for
    free from the machinery that already tracks steps.

    Injected capabilities (all optional, degrade honestly when absent):

    durable_fn     () -> int: the WAL's fsync watermark
                   (`harness.wal.ElasticWal.durable_seq`). None = no WAL:
                   ``durable`` acks honestly downgrade to ``applied``.
    watermarks_fn  () -> {origin: seq}: this worker's applied
                   watermarks (`ServePlane.applied_watermarks` shape) —
                   rides every ack so routers learn, and answers the
                   replication probes `WriteRouter` certifies
                   ``replicated_to_k`` with.
    pressure_fns   iterable of () -> Optional[int]: admission probes
                   (WAL durability lag, overlap-queue depth, pager
                   pressure). A non-None return sheds the write with
                   that retry_after_ms hint.
    """

    def __init__(
        self,
        member: str,
        metrics: Optional[Metrics] = None,
        durable_fn: Optional[Callable[[], int]] = None,
        watermarks_fn: Optional[Callable[[], Dict[str, int]]] = None,
        pressure_fns: Tuple[Callable[[], Optional[int]], ...] = (),
        queue_max: int = 256,
        ack_timeout_s: float = 2.0,
        ack_before_fsync: bool = False,
        mono: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        poll_s: float = 0.005,
    ):
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.durable_fn = durable_fn
        self.watermarks_fn = watermarks_fn
        self.pressure_fns = tuple(pressure_fns)
        self.queue_max = max(1, int(queue_max))
        self.ack_timeout_s = float(ack_timeout_s)
        self.ack_before_fsync = bool(ack_before_fsync)
        self.mono = mono
        self.sleep = sleep
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._pending: List[_PendingWrite] = []
        self._acked: Dict[str, Dict[str, Any]] = {}  # write_id -> ack doc
        # write_id -> its parked _PendingWrite, from enqueue until the
        # drain that folds it records the ack. A duplicate delivery in
        # this window attaches to the original instead of enqueueing a
        # second fold.
        self._inflight: Dict[str, _PendingWrite] = {}
        self._drain_rate = 0.0  # writes/s EWMA behind the shed hint

    # -- the round-loop side -------------------------------------------------

    def drain(self, seq: int, apply_fn: Callable[[List[Any]], None]) -> int:
        """Fold every parked write into the live state at step `seq`.
        ONE `apply_fn` call gets the whole drained batch (concatenated
        ops, arrival order) — the server-side half of the batching
        story. Each write is stamped ``(self.member, seq)``; transport
        threads blocked in `handle()` wake and build their acks. The
        write_id ack is recorded HERE, not in `handle()`: a write whose
        handler timed out before the fold still lands in the dedup
        cache, so a client retry re-acks instead of re-applying. A
        raising `apply_fn` fails the batch honestly (the writes were
        NOT applied and leave no dedup entry; callers see an error, and
        a retry legitimately re-applies)."""
        with self._lock:
            batch, self._pending = self._pending, []
        if not batch:
            return 0
        t0 = self.mono()
        try:
            apply_fn([op for w in batch for op in w.ops])
        except Exception as e:  # noqa: BLE001 — surfaced per-writer
            with self._lock:
                for w in batch:
                    if w.write_id is not None:
                        self._inflight.pop(w.write_id, None)
            for w in batch:
                w.error = f"apply failed: {e}"
                w.done.set()
            self.metrics.count("ingest.apply_failures")
            return 0
        t_fold = self.mono()
        dt = max(1e-9, t_fold - t0)
        inst = len(batch) / dt
        self._drain_rate = (
            inst if self._drain_rate == 0.0
            else 0.8 * self._drain_rate + 0.2 * inst
        )
        with self._lock:
            for w in batch:
                w.seq = int(seq)
                w.t_fold = t_fold
                w.kernel_ms = dt * 1e3
                if w.write_id is None:
                    continue
                # Atomically retire the in-flight entry and record the
                # base ack: a duplicate delivery sees exactly one of
                # them, never a gap it could re-apply through.
                self._inflight.pop(w.write_id, None)
                self._acked[w.write_id] = {
                    "write_ack": True,
                    "member": self.member,
                    "origin": self.member,
                    "seq": int(seq),
                    "level": ACK_APPLIED,
                    "write_id": w.write_id,
                }
            while len(self._acked) > _ACK_CACHE_MAX:
                self._acked.pop(next(iter(self._acked)))
        for w in batch:
            w.done.set()
            if w.write_id is not None:
                # Fleet-visible fold evidence: certify_writes replays
                # these to surface a write_id folded by >1 member (the
                # at-least-once failover case).
                obs_events.emit(
                    "ingest.fold", member=self.member, wseq=int(seq),
                    write_id=w.write_id, n_ops=len(w.ops),
                )
        self.metrics.count("ingest.applied", len(batch))
        return len(batch)

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def health_fields(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()["counters"]
        return {
            "ingest_queue_depth": self.depth(),
            "ingest_applied": int(snap.get("ingest.applied", 0)),
            "ingest_unsafe_acks": int(snap.get("ingest.unsafe_acks", 0)),
        }

    # -- the transport side --------------------------------------------------

    def handle(self, raw: bytes, surface: str = "local") -> bytes:
        """bytes -> canonical ack/error bytes, total: bad requests and
        shed decisions come back as honest error documents, never
        exceptions (the transport would close the connection and the
        writer could not tell a crash from a shed)."""
        self.metrics.count("ingest.writes")
        self.metrics.count(f"ingest.writes.{surface}")
        m_in = self.mono()
        try:
            faults.fire("serve.write")  # injected stall/raise per surface
            doc, framed = self._decode(raw)
        except faults.InjectedFault as e:
            return encode({"error": f"fault: {e}", "member": self.member})
        except ValueError as e:
            self.metrics.count("ingest.bad_requests")
            return encode(
                {"error": f"bad_request: {e}", "member": self.member}
            )
        probe = doc.get("probe")
        if probe is not None:
            return self._answer_probe(probe)
        ctx = rtrace.server_trace(doc)
        write_id = doc.get("write_id")
        ops = doc.get("ops")
        if not isinstance(ops, list) or not ops:
            self.metrics.count("ingest.bad_requests")
            return encode(
                {"error": "bad_request: no ops", "member": self.member}
            )
        level = str(doc.get("ack", ACK_DURABLE))
        if level not in _ACK_LEVELS:
            self.metrics.count("ingest.bad_requests")
            return encode(
                {"error": f"bad_request: unknown ack level {level!r}",
                 "member": self.member}
            )
        if framed:
            self.metrics.count("ingest.range_frames")
        wid = str(write_id) if write_id is not None else None
        deadline = self.mono() + self.ack_timeout_s
        # Pressure probes run OUTSIDE the lock (a probe may call back
        # into this plane's own introspection); the verdict is applied
        # under the lock below, after dedup has had first refusal.
        pressure = self._pressure_shed()
        w = _PendingWrite(ops, wid)
        w.t_stage = m_in
        prior: Optional[Dict[str, Any]] = None
        orig: Optional[_PendingWrite] = None
        shed: Optional[Dict[str, Any]] = None
        shed_kind = ""
        with self._lock:
            # Dedup first — a duplicate delivery (client retry, owner
            # redelivery racing the original) is re-acked or attached
            # to the in-flight original, NEVER shed and never enqueued
            # a second time.
            if wid is not None:
                prior = self._acked.get(wid)
                if prior is None:
                    orig = self._inflight.get(wid)
            if prior is None and orig is None:
                if pressure is not None:
                    shed, shed_kind = pressure, "pressure"
                elif len(self._pending) + 1 > self.queue_max:
                    # Bound check and append share this one lock hold:
                    # N racing handlers cannot all pass the depth test
                    # and push the queue past queue_max.
                    shed = self._queue_shed_doc(len(self._pending))
                    shed_kind = "queue"
                else:
                    self._pending.append(w)
                    if wid is not None:
                        self._inflight[wid] = w
        if prior is not None:
            return self._reack(prior, level, deadline, ctx, m_in)
        if orig is not None:
            return self._await_inflight(orig, level, deadline, ctx, m_in)
        if shed is not None:
            self.metrics.count(f"ingest.{shed_kind}_shed")
            self.metrics.count(f"ingest.shed.{surface}")
            return encode(self._attach_echo(shed, ctx, m_in, shed=True))
        w.done.wait(max(0.0, self.ack_timeout_s))
        if not w.done.is_set():
            # The round loop never drained us (worker wedged or dying):
            # fail honestly rather than hang the writer. The write may
            # still fold later — it stays registered in-flight, and the
            # drain records its ack, so a retry with this write_id
            # attaches or re-acks instead of re-applying.
            self.metrics.count("ingest.apply_timeouts")
            return encode(self._attach_echo(
                {"error": "unavailable: ingest apply timeout",
                 "member": self.member}, ctx, m_in,
            ))
        if w.error is not None:
            return encode(self._attach_echo(
                {"error": w.error, "member": self.member}, ctx, m_in,
            ))
        t_ba = self.mono()
        ack = self._build_ack(w.seq, w.write_id, level, deadline)
        dwait_ms = max(0.0, (self.mono() - t_ba) * 1e3)
        if w.write_id is not None:
            self._store_ack(w.write_id, ack)
        obs_events.emit(
            "ingest.write", wseq=w.seq, level=ack["level"],
            write_id=w.write_id or "", n_ops=len(ops),
        )
        # Per-tier time-to-ack histogram (receipt -> ack built, at the
        # tier actually ACHIEVED) — rides every scrape surface.
        self.metrics.observe(
            f"ingest.ack_ms.{ack['level']}",
            max(0.0, (self.mono() - m_in) * 1e3),
        )
        return encode(self._attach_echo(
            ack, ctx, m_in, w=w, durable_wait_ms=round(dwait_ms, 3),
        ))

    def handler_for(self, surface: str) -> Callable[[bytes], bytes]:
        """A bytes->bytes handler bound to one surface label, so the
        per-surface shed/write counters attribute correctly."""
        return lambda raw: self.handle(raw, surface=surface)

    # -- internals -----------------------------------------------------------

    def _decode(self, raw: bytes) -> Tuple[Dict[str, Any], bool]:
        """(request doc, was-CCRF-framed). A `WriteSession` burst
        arrives as one ``CCRF|lo|hi|payload`` range frame; bare JSON is
        the degenerate single-write frame."""
        blob = bytes(raw or b"")
        framed = blob[:4] == FRAME_MAGIC
        if framed:
            _lo, _hi, blob = decode_range_frame(blob, 0)
        try:
            doc = json.loads(blob.decode("utf-8"))
        except Exception as e:  # noqa: BLE001 — caller degrades
            raise ValueError(f"undecodable write: {e}") from e
        if not isinstance(doc, dict):
            raise ValueError("write payload must be a JSON object")
        return doc, framed

    def _answer_probe(self, probe: Any) -> bytes:
        """Replication probe: does THIS member's applied watermark cover
        ``(origin, seq)``? The router counts confirmations toward
        ``replicated_to_k`` — the replicas attest, not the owner."""
        self.metrics.count("ingest.probes")
        wm = self.watermarks_fn() if self.watermarks_fn is not None else {}
        doc: Dict[str, Any] = {
            "member": self.member,
            "watermarks": {str(o): int(s) for o, s in (wm or {}).items()},
        }
        if isinstance(probe, dict):
            o, s = str(probe.get("origin", "")), int(probe.get("seq", -1))
            doc["covers"] = bool(doc["watermarks"].get(o, -1) >= s >= 0)
        return encode(doc)

    def _pressure_shed(self) -> Optional[Dict[str, Any]]:
        """First non-None verdict from the injected pressure probes
        (WAL lag / overlap depth / pager) as an honest shed document;
        None = no pressure. Never called under the plane lock."""
        for fn in self.pressure_fns:
            try:
                hint = fn()
            except Exception:  # noqa: BLE001 — a broken probe never sheds
                continue
            if hint is not None:
                return {
                    "error": "overloaded: backpressure",
                    "member": self.member,
                    "retry_after_ms": max(1, min(5000, int(hint))),
                }
        return None

    def _queue_shed_doc(self, depth: int) -> Dict[str, Any]:
        """The queue-full shed document (retry_after from the observed
        drain rate). Caller holds the plane lock."""
        rate = self._drain_rate
        if rate <= 0.0:
            hint = 50
        else:
            hint = max(1, min(5000, int(1000.0 * (depth + 1) / rate)))
        return {
            "error": f"overloaded: ingest queue full ({depth} >= "
            f"{self.queue_max})",
            "member": self.member,
            "retry_after_ms": hint,
        }

    def _attach_echo(
        self,
        doc: Dict[str, Any],
        ctx: Optional[Dict[str, Any]],
        m_in: float,
        w: Optional[_PendingWrite] = None,
        **extra: Any,
    ) -> Dict[str, Any]:
        """Attach the rtrace server echo to a response doc — only when
        the request carried a trace context (untraced responses stay
        byte-identical to the pre-trace wire format). Returns a COPY:
        the success ack is also cached for write_id dedup, and a stale
        echo must never ride a future re-ack."""
        if ctx is None:
            return doc
        marks: Dict[str, Any] = {"m_in": m_in, "m_out": self.mono()}
        if w is not None and w.t_fold > 0.0:
            marks["m_stage"] = w.t_stage
            marks["m_fold"] = w.t_fold
            extra.setdefault("kernel_ms", round(w.kernel_ms, 3))
        if devprof.ACTIVE:
            # Device-observatory compile time inside this hop's window —
            # the write path's kernel-bucket honesty sub-annotation.
            cms = devprof.compile_ms_in_window(m_in, marks["m_out"])
            if cms > 0.0:
                extra.setdefault("compile_ms", cms)
        out = dict(doc)
        out["rtrace"] = rtrace.server_echo(ctx, self.member, marks, **extra)
        return out

    def _store_ack(self, wid: str, ack: Dict[str, Any]) -> None:
        with self._lock:
            self._acked[wid] = ack
            while len(self._acked) > _ACK_CACHE_MAX:
                self._acked.pop(next(iter(self._acked)))

    def _reack(
        self, prior: Dict[str, Any], level: str, deadline: float,
        ctx: Optional[Dict[str, Any]] = None, m_in: float = 0.0,
    ) -> bytes:
        """Re-answer a duplicate delivery from the recorded ack — same
        ``(origin, seq)``, no second fold. A drain-time base ack sits at
        ``applied``; if this delivery asks for durability, wait the
        watermark out against the ORIGINAL fold's seq and upgrade the
        cached doc, so a retry after an ack timeout still gets the level
        it paid for."""
        self.metrics.count("ingest.duplicate_acks")
        ack = dict(prior)
        want = _ACK_LEVELS.index(level)
        have = _ACK_LEVELS.index(str(ack.get("level", ACK_APPLIED)))
        if want > have:
            ack = self._build_ack(
                int(ack["seq"]), str(ack.get("write_id") or "") or None,
                level, deadline,
            )
            if _ACK_LEVELS.index(ack["level"]) > have and ack.get("write_id"):
                self._store_ack(ack["write_id"], dict(ack))
        ack["duplicate"] = True
        if m_in > 0.0:
            self.metrics.observe(
                f"ingest.ack_ms.{ack.get('level', ACK_APPLIED)}",
                max(0.0, (self.mono() - m_in) * 1e3),
            )
        # Failover retries land here: a minimal dup echo keeps their
        # waterfalls complete even though this delivery never folded.
        return encode(self._attach_echo(ack, ctx, m_in, dup=True))

    def _await_inflight(
        self, orig: _PendingWrite, level: str, deadline: float,
        ctx: Optional[Dict[str, Any]] = None, m_in: float = 0.0,
    ) -> bytes:
        """A duplicate delivery racing its still-parked original: wait
        on the ORIGINAL's fold instead of enqueueing a second
        _PendingWrite (two concurrent deliveries must fold once)."""
        self.metrics.count("ingest.duplicate_acks")
        orig.done.wait(max(0.0, deadline - self.mono()))
        if not orig.done.is_set():
            self.metrics.count("ingest.apply_timeouts")
            return encode(self._attach_echo(
                {"error": "unavailable: ingest apply timeout",
                 "member": self.member}, ctx, m_in,
            ))
        if orig.error is not None:
            return encode(self._attach_echo(
                {"error": orig.error, "member": self.member}, ctx, m_in,
            ))
        ack = self._build_ack(orig.seq, orig.write_id, level, deadline)
        ack["duplicate"] = True
        if m_in > 0.0:
            self.metrics.observe(
                f"ingest.ack_ms.{ack['level']}",
                max(0.0, (self.mono() - m_in) * 1e3),
            )
        return encode(self._attach_echo(ack, ctx, m_in, w=orig, dup=True))

    def _build_ack(
        self, seq: int, write_id: Optional[str], level: str, deadline: float
    ) -> Dict[str, Any]:
        """The ack document at the HIGHEST level achieved by `deadline`,
        never above the requested one and never above the truth."""
        achieved = ACK_APPLIED
        want_durable = level in (ACK_DURABLE, ACK_REPLICATED)
        if want_durable and self.ack_before_fsync:
            # The deliberately-violating arm: claim durability the fsync
            # has not delivered. certify_writes must convict this.
            achieved = ACK_DURABLE
            self.metrics.count("ingest.unsafe_acks")
        elif want_durable and self.durable_fn is not None:
            while self.mono() < deadline:
                try:
                    if int(self.durable_fn()) >= seq:
                        achieved = ACK_DURABLE
                        self.metrics.count("ingest.durable_acks")
                        break
                except Exception:  # noqa: BLE001 — treat as not-yet-durable
                    pass
                self.sleep(self.poll_s)
            else:
                self.metrics.count("ingest.ack_downgrades")
        elif want_durable:
            # No WAL on this worker: durability is not on offer.
            self.metrics.count("ingest.ack_downgrades")
        ack: Dict[str, Any] = {
            "write_ack": True,
            "member": self.member,
            "origin": self.member,
            "seq": int(seq),
            "level": achieved,
            "requested": level,
        }
        if write_id is not None:
            ack["write_id"] = write_id
        if self.watermarks_fn is not None:
            try:
                ack["watermarks"] = {
                    str(o): int(s)
                    for o, s in (self.watermarks_fn() or {}).items()
                }
            except Exception:  # noqa: BLE001 — watermarks are advisory
                pass
        return ack


class _WriteAttempt:
    __slots__ = ("peer", "cancel", "done", "result", "error", "t0")

    def __init__(self, peer: str):
        self.peer = peer
        self.cancel = threading.Event()
        self.done = threading.Event()
        self.result: Optional[bytes] = None
        self.error: Optional[BaseException] = None
        self.t0 = 0.0


class WriteRouter:
    """Client-side write router: owner affinity, SWIM-verdict failover,
    shared circuit breakers, bounded retries, honest sheds — the write
    twin of `FleetRouter`, minus hedging (a write hedge lands on a
    SECOND member, where the per-plane write_id dedup cannot see the
    first delivery — a guaranteed duplicate fold; the failover walk
    covers the latency case without it).

    `write()` never raises and never hangs: every outcome is a decoded
    ack document (augmented with ``"peer"``) or an honest error
    document (``unavailable`` / ``overloaded`` + retry_after_ms).

    Pass the read tier's `BreakerBoard` as `breakers` to share failure
    evidence across both tiers of one client."""

    def __init__(
        self,
        peers: Any,
        write_fn: Callable[[str, bytes, float, threading.Event], bytes],
        member: str = "writer",
        metrics: Optional[Metrics] = None,
        verdict_fn: Optional[Callable[[str], str]] = None,
        timeout_s: float = 2.0,
        retries: int = 2,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 1.0,
        breaker_failures: int = 3,
        breaker_cooldown_s: float = 2.0,
        replication_wait_s: float = 2.0,
        replication_poll_s: float = 0.05,
        probe_timeout_s: float = 0.5,
        mono: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        poll_s: float = 0.005,
        seed: int = 0,
        breakers: Optional[BreakerBoard] = None,
    ):
        self._peers_src = peers
        self.write_fn = write_fn
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.verdict_fn = verdict_fn
        self.timeout_s = float(timeout_s)
        self.retries = max(0, int(retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.replication_wait_s = float(replication_wait_s)
        self.replication_poll_s = float(replication_poll_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.mono = mono
        self.sleep = sleep
        self.poll_s = float(poll_s)
        self._rng = random.Random(seed)
        self._board = (
            breakers
            if breakers is not None
            else BreakerBoard(breaker_failures, breaker_cooldown_s, mono)
        )
        self._wid_lock = threading.Lock()
        self._wid_n = 0

    # -- introspection -------------------------------------------------------

    def _peers(self) -> List[str]:
        src = self._peers_src
        out = src() if callable(src) else src
        return [str(p) for p in out]

    def breaker(self, peer: str):
        return self._board.get(peer)

    def route(self, key: str) -> List[str]:
        """Owner-first candidate list: plain HRW (no staleness demotion
        — owner affinity must not wobble with lag), dead peers and open
        breakers dropped read-only."""
        return candidate_order(
            key, self._peers(), verdict_fn=self.verdict_fn,
            breakers=self._board,
        )

    def status(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()["counters"]
        return {
            "breakers": self._board.states(),
            "counters": {
                k: v for k, v in snap.items()
                if k.startswith("router.write")
            },
        }

    # -- the write path ------------------------------------------------------

    def write(
        self,
        ops: List[Any],
        key: str,
        ack: str = ACK_DURABLE,
        k: int = 2,
        session: Optional[Any] = None,
        write_id: Optional[str] = None,
        payload: Optional[bytes] = None,
        trace: Optional[rtrace.Trace] = None,
    ) -> Dict[str, Any]:
        """Route one write (or one pre-framed burst via `payload` — a
        `WriteSession` CCRF range frame whose inner doc must carry the
        SAME write_id). Walks the HRW owner list with bounded retries;
        redelivery to the same plane is deduped by write_id, while
        failover to a different member is at-least-once (see
        `_run_pass`). On success teaches the session its own ``(origin,
        seq)`` and flight-records ``ingest.ack`` — the feed
        `obs.audit.certify_writes` replays. A `WriteSession` that
        pre-framed its burst mints the trace itself (the context must
        sit INSIDE the CCRF payload) and hands it over via `trace`."""
        t0 = self.mono()
        self.metrics.count("router.writes")
        if ack not in _ACK_LEVELS:
            return {"error": f"bad_request: unknown ack level {ack!r}"}
        if write_id is None:
            with self._wid_lock:
                self._wid_n += 1
                write_id = f"{self.member}:{self._wid_n}"
        tr = trace
        if tr is None and payload is None and rtrace.ACTIVE:
            tr = rtrace.begin("write", key, t0)
        if payload is None:
            doc: Dict[str, Any] = {
                "write_id": write_id, "ops": list(ops), "ack": ack,
            }
            if tr is not None:
                w = tr.wire()
                if w:
                    doc["trace"] = w
            payload = encode(doc)
        sess = session if isinstance(session, ClientSession) else None

        last_err: Optional[str] = None
        shed_hint: Optional[int] = None
        all_sheds = True
        round_i = 0
        first_route = True
        while round_i <= self.retries:
            # First route hop opens at t0: write_id mint + CCRF/JSON
            # payload build is route-bucket work, not a coverage gap.
            t_route = t0 if first_route else self.mono()
            first_route = False
            order = self.route(key)
            if tr is not None:
                tr.hop("route", t_route, self.mono(),
                       candidates=list(order),
                       breakers={p: s for p, s
                                 in self._board.states().items()
                                 if s != "closed"})
            if not order:
                last_err = last_err or "no eligible peers"
                all_sheds = False
                round_i += 1
                self._backoff(round_i, tr)
                continue
            outcome, detail = self._run_pass(order, payload, tr)
            if outcome == "ok":
                resp, peer = detail
                return self._finish_ok(
                    t0, resp, peer, ack, k, write_id, sess, tr
                )
            if outcome == "shed":
                shed_hint = max(shed_hint or 0, int(detail or 0))
                last_err = "overloaded"
            else:
                all_sheds = False
                last_err = str(detail)
            round_i += 1
            if round_i <= self.retries:
                self.metrics.count("router.write_retries")
                self._backoff(round_i, tr)
        if shed_hint is not None and all_sheds:
            self.metrics.count("router.write_shed_returns")
            return self._finish_error(
                t0, "overloaded", {"retry_after_ms": shed_hint}, tr=tr,
            )
        return self._finish_error(
            t0, "unavailable", {"detail": last_err},
            counter="router.write_exhausted", tr=tr,
        )

    # -- one pass over the owner list ----------------------------------------

    def _run_pass(
        self, order: List[str], payload: bytes,
        tr: Optional[rtrace.Trace] = None,
    ) -> Tuple[str, Any]:
        """("ok", (resp, peer)) | ("shed", retry_after_ms) |
        ("err", detail). A failed owner fails over to the next HRW
        candidate (`router.write_failovers`) with the SAME write_id.
        Redelivery to the same plane dedups (in-flight registry + ack
        cache); failover to a DIFFERENT member is at-least-once — if
        the first owner folded before dying, the successor re-applies
        under its own (origin, seq) and the CRDT join must absorb the
        duplicate (certify_writes surfaces it via ingest.fold
        evidence)."""
        shed_hint: Optional[int] = None
        saw_shed = False
        last_detail: Any = "no candidates"
        for idx, peer in enumerate(order):
            if idx:
                self.metrics.count("router.write_failovers")
            if faults.ACTIVE:
                try:
                    if faults.fire("router.write") == "drop":
                        raise ConnectionError("router.write: injected drop")
                except (faults.InjectedFault, ConnectionError) as e:
                    self._fail(peer, e)
                    last_detail = str(e)
                    continue
            verdict, detail = self._attempt(peer, payload, tr)
            if verdict != "ok":
                last_detail = detail
                continue
            resp, who, a0, a1 = detail
            kind, fine = self._classify(who, resp, tr, a0, a1)
            if kind == "ok":
                return ("ok", (fine, who))
            if kind == "shed":
                saw_shed = True
                shed_hint = max(shed_hint or 0, int(fine or 0))
                last_detail = "overloaded"
            else:
                last_detail = fine
        if saw_shed:
            return ("shed", shed_hint)
        return ("err", last_detail)

    def _attempt(
        self, peer: str, payload: bytes,
        tr: Optional[rtrace.Trace] = None,
    ) -> Tuple[str, Any]:
        """One write attempt on a worker thread; the main thread watches
        the SWIM verdict (dead -> cancel + fail over NOW, not at the
        timeout) and the deadline. Returns
        ("ok", (raw, peer, t_send, t_recv)) or ("fail", detail)."""
        t_entry = self.mono()
        self.metrics.count("router.write_attempts")
        self.breaker(peer).allow()  # reserve any half-open probe slot
        att = _WriteAttempt(peer)
        # Window opens at _attempt entry so breaker/thread setup lands
        # in the attempt (wire) bucket instead of escaping attribution.
        att.t0 = t_entry

        def run() -> None:
            try:
                att.result = self.write_fn(
                    peer, payload, self.timeout_s, att.cancel
                )
            except BaseException as e:  # noqa: BLE001 — surfaced via att.error
                att.error = e
            finally:
                att.done.set()

        threading.Thread(
            target=run, name=f"router-w-{peer}", daemon=True
        ).start()
        deadline = att.t0 + self.timeout_s
        while not att.done.is_set():
            now = self.mono()
            if now >= deadline:
                break
            if (
                self.verdict_fn is not None
                and self.verdict_fn(peer) == "dead"
            ):
                # SWIM buried the owner mid-write: the write may or may
                # not have folded — fail over and let the write_id dedup
                # disambiguate at the successor.
                att.cancel.set()
                self.metrics.count("router.write_dead_reroutes")
                self._fail(peer, TimeoutError("owner died mid-write"))
                if tr is not None:
                    tr.hop("dead_reroute", now, peer=peer)
                    tr.hop("attempt", att.t0, now, peer=peer, ok=False,
                           err="dead mid-write")
                return ("fail", f"{peer} dead mid-write")
            self.sleep(self.poll_s)
        now = self.mono()
        if att.done.is_set() and att.error is None:
            self._succeed(att)
            if tr is not None:
                tr.hop("attempt", att.t0, now, peer=peer, ok=True)
            return ("ok", (att.result, peer, att.t0, now))
        att.cancel.set()
        if att.done.is_set():
            self._fail(peer, att.error or TimeoutError("write failed"))
            if tr is not None:
                tr.hop("attempt", att.t0, now, peer=peer, ok=False,
                       err=str(att.error))
            return ("fail", f"{peer}: {att.error}")
        self.metrics.count("router.write_timeouts")
        self._fail(peer, TimeoutError("write deadline exceeded"))
        if tr is not None:
            tr.hop("attempt", att.t0, now, peer=peer, ok=False,
                   err="timeout")
        return ("fail", f"{peer}: timeout after {self.timeout_s}s")

    # -- response classification ---------------------------------------------

    def _classify(
        self, peer: str, raw: Optional[bytes],
        tr: Optional[rtrace.Trace] = None,
        t_send: Optional[float] = None, t_recv: Optional[float] = None,
    ) -> Tuple[str, Any]:
        try:
            resp = json.loads(bytes(raw or b"").decode("utf-8"))
        except Exception as e:  # noqa: BLE001 — garbage == peer failure
            self.metrics.count("router.write_errors")
            self._fail(peer, e)
            return ("err", f"{peer}: undecodable ack: {e}")
        echo = resp.pop("rtrace", None) if isinstance(resp, dict) else None
        if tr is not None and isinstance(echo, dict) \
                and t_send is not None and t_recv is not None:
            tr.absorb_echo(echo, t_send, t_recv)
        if tr is not None and t_recv is not None:
            # Ack decode/verdict time rides the route bucket (mirrors
            # the read router) so sub-ms writes keep full coverage.
            tr.hop("route", t_recv, self.mono(), step="classify",
                   peer=peer)
        err = resp.get("error")
        if err is not None:
            err_s = str(err)
            if err_s.startswith("overloaded"):
                # Admission control, not peer sickness: no breaker hit.
                self.metrics.count("router.write_sheds")
                return ("shed", resp.get("retry_after_ms", 0))
            self.metrics.count("router.write_errors")
            self._fail(peer, RuntimeError(err_s))
            return ("err", f"{peer}: {err_s}")
        if not resp.get("write_ack") or "seq" not in resp:
            self.metrics.count("router.write_errors")
            self._fail(peer, RuntimeError("malformed ack"))
            return ("err", f"{peer}: malformed ack")
        return ("ok", resp)

    # -- ack finishing -------------------------------------------------------

    def _finish_ok(
        self,
        t0: float,
        resp: Dict[str, Any],
        peer: str,
        requested: str,
        k: int,
        write_id: str,
        sess: Optional[ClientSession],
        tr: Optional[rtrace.Trace] = None,
    ) -> Dict[str, Any]:
        out = dict(resp)
        out["peer"] = peer
        origin = str(resp.get("origin", peer))
        seq = int(resp.get("seq", -1))
        if (
            requested == ACK_REPLICATED
            and str(out.get("level")) == ACK_DURABLE
        ):
            t_probe = self.mono()
            confirmed = self._confirm_replication(origin, seq, int(k), peer)
            if tr is not None:
                tr.hop("ack_probe", t_probe, self.mono(),
                       confirmed=int(confirmed), want=int(k))
            out["replication"] = {"confirmed": confirmed, "want": int(k)}
            if confirmed >= int(k):
                out["level"] = ACK_REPLICATED
                self.metrics.count("router.replicated_acks")
            else:
                self.metrics.count("router.replication_timeouts")
        self.metrics.count("router.write_successes")
        dt = max(0.0, self.mono() - t0)
        self.metrics.merge({"latencies": {"router.write": [dt]}})
        rtrace.commit(tr, "ok", dt * 1e3)
        # The certifier's feed: what the CLIENT was told it holds.
        obs_events.emit(
            "ingest.ack", peer=peer, origin=origin, wseq=seq,
            level=str(out.get("level", "")), write_id=write_id,
            requested=requested,
        )
        if sess is not None and seq >= 0:
            # Read-your-writes closes across tiers right here: the read
            # router now routes this session only to peers whose applied
            # watermarks cover (origin, seq).
            sess.note_write(origin, seq)
        return out

    def _confirm_replication(
        self, origin: str, seq: int, k: int, owner: str
    ) -> int:
        """Poll the replicas themselves until k distinct members
        (counting the owner) confirm their applied watermark covers
        ``(origin, seq)``, bounded by `replication_wait_s`.

        The peers are probed in PARALLEL (one thread each): with p
        replicas at probe RTT t, the serial walk cost O(p·t) per ack
        and a single slow replica stalled every probe behind it. Each
        thread re-polls only ITS peer until it confirms; the first k
        confirmations release the waiter immediately (`enough`), and
        stragglers are cancelled rather than waited out."""
        if seq < 0:
            return 0
        confirmed = {owner}
        if len(confirmed) >= k:
            return len(confirmed)
        lock = threading.Lock()
        enough = threading.Event()
        probe = encode({"probe": {"origin": origin, "seq": seq}})
        deadline = self.mono() + self.replication_wait_s
        cancel = threading.Event()

        def probe_peer(peer: str) -> None:
            while not enough.is_set() and self.mono() < deadline:
                if (
                    self.verdict_fn is not None
                    and self.verdict_fn(peer) == "dead"
                ):
                    self.sleep(self.replication_poll_s)
                    continue
                try:
                    raw = self.write_fn(
                        peer, probe, self.probe_timeout_s, cancel
                    )
                    resp = json.loads(bytes(raw).decode("utf-8"))
                except Exception:  # noqa: BLE001 — probe failure != write failure
                    self.sleep(self.replication_poll_s)
                    continue
                wm = resp.get("watermarks")
                if (
                    resp.get("covers")
                    or (isinstance(wm, dict)
                        and int(wm.get(origin, -1)) >= seq)
                ):
                    with lock:
                        confirmed.add(peer)
                        self.metrics.count("router.replication_confirms")
                        if len(confirmed) >= k:
                            enough.set()
                    return
                self.sleep(self.replication_poll_s)

        threads = [
            threading.Thread(
                target=probe_peer, args=(p,),
                name=f"router-probe-{p}", daemon=True,
            )
            for p in self._peers() if p != owner
        ]
        for t in threads:
            t.start()
        while (
            not enough.is_set()
            and self.mono() < deadline
            and any(t.is_alive() for t in threads)
        ):
            self.sleep(self.poll_s)
        enough.set()   # release pollers still sleeping out the deadline
        cancel.set()   # and any probe blocked in the transport
        with lock:
            return len(confirmed)

    def _finish_error(
        self,
        t0: float,
        error: str,
        extra: Dict[str, Any],
        counter: Optional[str] = None,
        tr: Optional[rtrace.Trace] = None,
    ) -> Dict[str, Any]:
        if counter:
            self.metrics.count(counter)
        dt = max(0.0, self.mono() - t0)
        self.metrics.merge({"latencies": {"router.write": [dt]}})
        obs_events.emit("router.write_give_up", error=error)
        if tr is not None:
            outcome = "shed" if error == "overloaded" else "failed"
            if outcome == "failed" \
                    and "timeout" in str(extra.get("detail", "")):
                outcome = "deadline"
            rtrace.commit(tr, outcome, dt * 1e3)
        out: Dict[str, Any] = {"error": error}
        out.update(extra)
        return out

    # -- bookkeeping ---------------------------------------------------------

    def _succeed(self, att: _WriteAttempt) -> None:
        if self.breaker(att.peer).record_success():
            self.metrics.count("router.write_breaker_closes")

    def _fail(self, peer: str, err: BaseException) -> None:
        if isinstance(err, TimeoutError) or "timed out" in str(err):
            self.metrics.count("router.write_peer_timeouts")
        if self.breaker(peer).record_failure():
            self.metrics.count("router.write_breaker_opens")

    def _backoff(
        self, round_i: int, tr: Optional[rtrace.Trace] = None
    ) -> None:
        base = min(
            self.backoff_max_s, self.backoff_base_s * (2 ** (round_i - 1))
        )
        a = self.mono()
        self.sleep(base * (0.5 + self._rng.random()))  # jitter in [0.5, 1.5)
        if tr is not None:
            tr.hop("backoff", a, self.mono(), round=round_i)


def tcp_write_fn(
    addrs: Any, connect_timeout_s: float = 0.5
) -> Callable[[str, bytes, float, threading.Event], bytes]:
    """Adapter: a `write_fn` over `net.tcp.write_peer` given `addrs` —
    a dict (or callable returning one) of peer -> (host, port). Raises
    KeyError for unknown peers (the router fails over)."""
    from ..net.tcp import write_peer

    def fn(
        peer: str, payload: bytes, timeout_s: float, cancel: threading.Event
    ) -> bytes:
        table = addrs() if callable(addrs) else addrs
        addr = table[peer]
        _member, resp = write_peer(
            tuple(addr), payload, timeout=timeout_s, cancel=cancel,
            connect_timeout=connect_timeout_s,
        )
        return resp

    return fn
