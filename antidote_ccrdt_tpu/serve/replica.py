"""Device-resident read replica: double-buffered snapshots of merged state.

The write path (sweep/overlap) mutates the worker's carried state through
donated jit slots (`core.batch_merge.merge_slots`) — holding a bare
reference to it from a concurrent reader thread would race buffer
donation. The replica therefore owns its buffers outright: `swap` runs
one jitted whole-tree device copy (`core.batch_merge.snapshot_state`,
the same slot discipline as the overlap pipeline's merge slots) and
publishes the copy by atomic reference flip into a two-slot ring.
Readers grab the live slot without any lock on the query hot path;
the previous slot stays intact until the swap after next, so a query
mid-answer on the old snapshot never sees a freed buffer either.

Each snapshot carries its staleness pedigree, stamped at swap time on
the worker's OWN monotonic clock:

* ``seq``          the publish seq this snapshot reflects (`as_of_seq`);
* ``swap_mono``    when the copy was taken;
* ``lag_bound_s``  the worker's replication-lag bound at that instant
                   (max over peers of lag seconds + staleness seconds,
                   from `obs.lag.LagTracker`) — how far behind the
                   fleet's writes this state could already have been
                   WHEN it was captured.

`ServePlane` turns the pair into the advertised
``staleness_bound_s = (now - swap_mono) + lag_bound_s``: every term is
a difference of one process's monotonic clock, so cross-host clock skew
cannot shrink the bound (tests/test_serve_staleness.py pins this under
asymmetric simulated skew).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..core import batch_merge
from ..obs import events as obs_events
from ..obs import spans as obs_spans


class Snapshot:
    """One immutable read-replica buffer plus its staleness pedigree.

    ``view`` is the lazily-attached host materialization
    (`serve.kernels.SnapshotView`) — None until the first query against
    this snapshot forces it (that miss/hit split is the snapshot cache).
    """

    __slots__ = ("state", "seq", "swap_mono", "lag_bound_s", "view")

    def __init__(self, state: Any, seq: int, swap_mono: float, lag_bound_s: float):
        self.state = state
        self.seq = int(seq)
        self.swap_mono = float(swap_mono)
        self.lag_bound_s = float(lag_bound_s)
        self.view: Any = None


class ReadReplica:
    """Two-slot snapshot ring: `swap` publishes, `live` reads lock-free."""

    def __init__(
        self,
        metrics: Any = None,
        mono: Callable[[], float] = time.monotonic,
    ):
        self.metrics = metrics
        self.mono = mono  # injectable: sim drills pass the skewed virtual clock
        self._swap_lock = threading.Lock()
        self._bufs: list = [None, None]
        self._live = 0

    def swap(
        self,
        state: Any,
        seq: int,
        lag_bound_s: float = 0.0,
        resolve: Optional[Callable[[Any], Any]] = None,
    ) -> Snapshot:
        """Copy `state` to a fresh device buffer and make it the live
        snapshot. Called from the worker's round thread at publish
        boundaries; queries racing the swap keep reading the old slot
        until the single reference flip below. `resolve` maps the carried
        state to the logical state first — the pager hook (`full_state`)
        joins demoted partitions back in so reads never see a hole."""
        tok = (
            obs_spans.begin("round.serve_swap", seq=int(seq))
            if obs_spans.ACTIVE
            else None
        )
        try:
            if resolve is not None:
                state = resolve(state)
            with self._swap_lock:
                snap = Snapshot(
                    batch_merge.snapshot_state(state),
                    seq,
                    self.mono(),
                    lag_bound_s,
                )
                idx = 1 - self._live
                self._bufs[idx] = snap
                self._live = idx  # the atomic publish: readers see old or new
        finally:
            obs_spans.end(tok)
        if self.metrics is not None:
            self.metrics.count("serve.swaps")
            # Mesh-sharded states: the jitted copy preserves the input
            # sharding (an identity keeps its operand's layout), so the
            # replica holds per-device shards, never a gathered whole —
            # gauge how many device shards the live snapshot spans so
            # the obs plane can prove reads stayed shard-resident.
            try:
                import jax

                leaf = next(iter(jax.tree_util.tree_leaves(snap.state)), None)
                sharding = getattr(leaf, "sharding", None)
                if sharding is not None:
                    self.metrics.set(
                        "serve.replica_shards", float(len(sharding.device_set))
                    )
            except Exception:  # noqa: BLE001 — gauge only, stay total
                pass
        obs_events.emit(
            "serve.swap", seq=snap.seq, lag_bound_s=round(snap.lag_bound_s, 6)
        )
        return snap

    def live(self) -> Optional[Snapshot]:
        """The current snapshot (None before the first swap). Lock-free:
        one list read of a slot only `swap` reassigns."""
        return self._bufs[self._live]

    def previous(self) -> Optional[Snapshot]:
        """The snapshot one swap back (still intact — its buffers are
        only reused by the swap after next)."""
        return self._bufs[1 - self._live]
