"""serve/: the read-serving plane (PR 9).

Reads dwarf writes at the ROADMAP's "millions of users" scale, and until
now the only read path was calling `value()` in-process. This package
serves bounded-staleness reads off the elastic worker's replicated
state:

* `replica`  — device-resident double-buffered snapshots, swapped at
               publish boundaries so queries never race the donated
               merge slots;
* `kernels`  — per-type batched query answering: one fold+observe
               dispatch materializes every key, queries are gathers;
* `cache`    — hot-key answers that outlive swaps, bounded by LRU and
               the staleness-pedigree horizon;
* `plane`    — the `ServePlane` facade all three wire surfaces call
               (`net/tcp.py` `{query}` frame, bridge `{query}` op,
               `POST /query` on `obs/http.py`).

Workers opt in via ``CCRDT_SERVE=1`` (`install_from_env`, the same
env-propagation pattern as `utils.faults` / `obs.http`).

PR 16 adds the WRITE tier: `ingest` (`IngestPlane` worker-side front
door + `WriteRouter` client-side owner routing over the shared
`routing_common` breakers) and `write_session` (`WriteSession` staging
+ pre-wire compaction). Workers opt in via ``CCRDT_INGEST=1``
(`install_ingest_from_env`).
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .cache import HotKeyCache
from .kernels import SnapshotView, answer, answer_one, materialize, query_key
from .plane import (
    Overloaded,
    ServePlane,
    SessionUncovered,
    encode,
    request_bytes,
)
from .ingest import (
    ACK_APPLIED,
    ACK_DURABLE,
    ACK_REPLICATED,
    IngestPlane,
    WriteRouter,
    tcp_write_fn,
)
from .replica import ReadReplica, Snapshot
from .router import CircuitBreaker, FleetRouter, tcp_query_fn
from .routing_common import BreakerBoard, candidate_order
from .session import ClientSession, SessionToken, covers, session_doc
from .write_session import WriteSession, effect_from_wire, effect_to_wire

ENV_FLAG = "CCRDT_SERVE"
INGEST_ENV_FLAG = "CCRDT_INGEST"

_FALSE = {"", "0", "false", "no", "off"}

__all__ = [
    "ACK_APPLIED",
    "ACK_DURABLE",
    "ACK_REPLICATED",
    "ENV_FLAG",
    "INGEST_ENV_FLAG",
    "BreakerBoard",
    "CircuitBreaker",
    "ClientSession",
    "FleetRouter",
    "HotKeyCache",
    "IngestPlane",
    "Overloaded",
    "ReadReplica",
    "ServePlane",
    "SessionToken",
    "SessionUncovered",
    "Snapshot",
    "SnapshotView",
    "WriteRouter",
    "WriteSession",
    "answer",
    "answer_one",
    "candidate_order",
    "covers",
    "effect_from_wire",
    "effect_to_wire",
    "encode",
    "install_from_env",
    "install_ingest_from_env",
    "materialize",
    "query_key",
    "request_bytes",
    "session_doc",
    "tcp_query_fn",
    "tcp_write_fn",
]


def install_from_env(
    dense: Any,
    member: str,
    metrics: Any = None,
    lag_tracker: Any = None,
    env: Optional[dict] = None,
) -> Optional[ServePlane]:
    """Build a `ServePlane` iff ``CCRDT_SERVE`` is truthy — workers call
    this unconditionally, like `faults.install_from_env`. Returns None
    when serving is off (the default: pure write fleets pay nothing)."""
    raw = (env if env is not None else os.environ).get(ENV_FLAG, "")
    if raw.strip().lower() in _FALSE:
        return None
    return ServePlane(
        dense, member=member, metrics=metrics, lag_tracker=lag_tracker
    )


def install_ingest_from_env(
    member: str,
    metrics: Any = None,
    durable_fn: Any = None,
    watermarks_fn: Any = None,
    pressure_fns: Any = (),
    env: Optional[dict] = None,
) -> Optional[IngestPlane]:
    """Build an `IngestPlane` iff ``CCRDT_INGEST`` is truthy — the write
    tier's twin of `install_from_env`. ``CCRDT_ACK_BEFORE_FSYNC=1``
    arms the deliberately-violating ack-before-fsync mode (chaos drills
    only: `obs.audit.certify_writes` must convict it).
    ``CCRDT_INGEST_ACK_TIMEOUT_S`` stretches the ack deadline — a write
    is only applied at the NEXT step boundary, so the deadline must
    exceed the worker's step cadence (contended CPU hosts step slowly;
    the chaos drills raise it there)."""
    e = env if env is not None else os.environ
    raw = e.get(INGEST_ENV_FLAG, "")
    if raw.strip().lower() in _FALSE:
        return None
    unsafe = e.get("CCRDT_ACK_BEFORE_FSYNC", "").strip().lower() not in _FALSE
    try:
        ack_timeout_s = float(e.get("CCRDT_INGEST_ACK_TIMEOUT_S", "2.0"))
    except ValueError:
        ack_timeout_s = 2.0
    return IngestPlane(
        member,
        metrics=metrics,
        durable_fn=durable_fn,
        watermarks_fn=watermarks_fn,
        pressure_fns=tuple(pressure_fns),
        ack_timeout_s=ack_timeout_s,
        ack_before_fsync=unsafe,
    )
