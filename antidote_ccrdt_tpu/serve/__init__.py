"""serve/: the read-serving plane (PR 9).

Reads dwarf writes at the ROADMAP's "millions of users" scale, and until
now the only read path was calling `value()` in-process. This package
serves bounded-staleness reads off the elastic worker's replicated
state:

* `replica`  — device-resident double-buffered snapshots, swapped at
               publish boundaries so queries never race the donated
               merge slots;
* `kernels`  — per-type batched query answering: one fold+observe
               dispatch materializes every key, queries are gathers;
* `cache`    — hot-key answers that outlive swaps, bounded by LRU and
               the staleness-pedigree horizon;
* `plane`    — the `ServePlane` facade all three wire surfaces call
               (`net/tcp.py` `{query}` frame, bridge `{query}` op,
               `POST /query` on `obs/http.py`).

Workers opt in via ``CCRDT_SERVE=1`` (`install_from_env`, the same
env-propagation pattern as `utils.faults` / `obs.http`).
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .cache import HotKeyCache
from .kernels import SnapshotView, answer, answer_one, materialize, query_key
from .plane import (
    Overloaded,
    ServePlane,
    SessionUncovered,
    encode,
    request_bytes,
)
from .replica import ReadReplica, Snapshot
from .router import CircuitBreaker, FleetRouter, tcp_query_fn
from .session import ClientSession, SessionToken, covers, session_doc

ENV_FLAG = "CCRDT_SERVE"

_FALSE = {"", "0", "false", "no", "off"}

__all__ = [
    "ENV_FLAG",
    "CircuitBreaker",
    "ClientSession",
    "FleetRouter",
    "HotKeyCache",
    "Overloaded",
    "ReadReplica",
    "ServePlane",
    "SessionToken",
    "SessionUncovered",
    "Snapshot",
    "SnapshotView",
    "answer",
    "answer_one",
    "covers",
    "encode",
    "install_from_env",
    "materialize",
    "query_key",
    "request_bytes",
    "session_doc",
    "tcp_query_fn",
]


def install_from_env(
    dense: Any,
    member: str,
    metrics: Any = None,
    lag_tracker: Any = None,
    env: Optional[dict] = None,
) -> Optional[ServePlane]:
    """Build a `ServePlane` iff ``CCRDT_SERVE`` is truthy — workers call
    this unconditionally, like `faults.install_from_env`. Returns None
    when serving is off (the default: pure write fleets pay nothing)."""
    raw = (env if env is not None else os.environ).get(ENV_FLAG, "")
    if raw.strip().lower() in _FALSE:
        return None
    return ServePlane(
        dense, member=member, metrics=metrics, lag_tracker=lag_tracker
    )
