"""`ServePlane`: the query front-end every wire surface shares.

One plane per worker ties the pieces together:

* `ReadReplica` — double-buffered device snapshots, swapped at publish
  boundaries by the worker's round thread (`swap`);
* a **bounded batching queue** — concurrent listener threads enqueue
  their decoded queries and one drainer answers the whole accumulated
  batch against a single snapshot materialization (the "one dispatch,
  thousands of queries" shape). Overflow sheds loudly
  (`serve.queue_shed` + an ``overloaded`` error response) instead of
  queueing unboundedly;
* `HotKeyCache` — answers outlive swaps; the `max_staleness_s` request
  knob decides whether an aged entry still qualifies, falls through to
  the fresh replica, or rejects (`serve.stale_rejects`);
* the **staleness contract** — every served value carries
  ``(value, as_of_seq, staleness_bound_s)`` with
  ``bound = (now - swap_mono) + lag_bound_at_swap``, all differences of
  this worker's monotonic clock (skew-immune; rounded UP to the µs so
  formatting can never shave the bound below truth).

Wire surfaces call ONE method — ``handle(request_bytes) ->
response_bytes`` — and transport the bytes verbatim, which is what
makes the tri-surface parity test (`tests/test_serve_parity.py`)
byte-exact: the codec is canonical JSON (sorted keys, compact
separators), so identical questions at identical snapshots produce
identical bytes on the TCP frame, the bridge op, and POST /query.

Request:  ``{"queries": [{"op": "value"|"topk"|"range", "key": int,
            "k"?: int, "lo"?: int, "hi"?: int}, ...],
            "max_staleness_s"?: float}``
Response: ``{"member": str, "n": int, "results": [
            {"value": ..., "as_of_seq": int, "staleness_bound_s": float}
            | {"error": ...}, ...]}``

`utils.faults` point ``serve.query`` fires at the top of `handle` on
every surface, so injected stalls/raises exercise each listener's own
degrade path (connection close / error frame / HTTP 500 — never a
hang).
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import events as obs_events
from ..utils import faults
from ..utils.metrics import Metrics
from . import kernels
from .cache import HotKeyCache
from .replica import ReadReplica


class Overloaded(RuntimeError):
    """The bounded query queue is full; the caller is shed."""


def encode(doc: Dict[str, Any]) -> bytes:
    """Canonical response/request bytes: sorted keys, compact
    separators — the tri-surface byte-identity anchor."""
    return (json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n").encode(
        "utf-8"
    )


def request_bytes(
    queries: List[Dict[str, Any]], max_staleness_s: Optional[float] = None
) -> bytes:
    doc: Dict[str, Any] = {"queries": list(queries)}
    if max_staleness_s is not None:
        doc["max_staleness_s"] = float(max_staleness_s)
    return encode(doc)


def _ceil6(x: float) -> float:
    """Round a staleness bound UP at µs precision — conservative by
    construction (a bound may only ever grow in transit)."""
    return math.ceil(max(0.0, x) * 1e6) / 1e6


class _Pending:
    __slots__ = ("queries", "max_staleness", "done", "results", "error")

    def __init__(self, queries: List[Dict[str, Any]], max_staleness: Optional[float]):
        self.queries = queries
        self.max_staleness = max_staleness
        self.done = False
        self.results: Optional[List[Any]] = None
        self.error: Optional[BaseException] = None


class _Batcher:
    """Bounded accumulate-and-drain queue. Any caller thread may become
    the drainer: the first arriver while no drain is running takes the
    whole pending list and answers it in one pass; threads that enqueued
    meanwhile wait on the condition and either find their result ready
    or become the next drainer. No dedicated thread, no idle latency —
    a lone request drains itself immediately, a burst coalesces."""

    def __init__(self, exec_batch: Callable[[List[_Pending]], None],
                 queue_max: int, metrics: Metrics):
        self._exec = exec_batch
        self.queue_max = max(1, int(queue_max))
        self.metrics = metrics
        self._cv = threading.Condition()
        self._pending: List[_Pending] = []
        self._busy = False

    def run(self, queries: List[Dict[str, Any]],
            max_staleness: Optional[float]) -> List[Any]:
        p = _Pending(queries, max_staleness)
        with self._cv:
            depth = sum(len(x.queries) for x in self._pending)
            if depth + len(queries) > self.queue_max:
                self.metrics.count("serve.queue_shed")
                raise Overloaded(
                    f"query queue full ({depth}+{len(queries)} > {self.queue_max})"
                )
            self._pending.append(p)
            while not p.done and self._busy:
                self._cv.wait(0.05)
            if not p.done:
                self._busy = True
                batch, self._pending = self._pending, []
        if not p.done:
            try:
                self._exec(batch)
            finally:
                # A drainer that died mid-batch must not strand followers.
                for x in batch:
                    if not x.done:
                        x.error = x.error or RuntimeError("batch aborted")
                        x.done = True
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()
        if p.error is not None:
            raise p.error
        return p.results or []


class ServePlane:
    """One worker's read-serving plane (see module docstring)."""

    def __init__(
        self,
        dense: Any,
        member: str = "?",
        metrics: Optional[Metrics] = None,
        lag_tracker: Any = None,
        mono: Callable[[], float] = time.monotonic,
        cache_cap: int = 1024,
        queue_max: int = 4096,
        meta_keep: int = 8,
        pager: Any = None,
    ):
        self.dense = dense
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.lag_tracker = lag_tracker
        # Out-of-core residency (core/pager.py): swaps resolve the
        # LOGICAL state (device ⊔ cold substrate) so reads never see a
        # demoted partition's hole, and answered row ids feed the
        # pager's recency clock — the serve plane IS the access stream
        # the eviction policy ranks partitions by.
        self.pager = pager
        self.mono = mono  # injectable: frozen in parity tests, virtual in sim
        self.replica = ReadReplica(metrics=self.metrics, mono=mono)
        self.cache = HotKeyCache(cap=cache_cap, metrics=self.metrics)
        self.meta_keep = max(1, int(meta_keep))
        # seq -> (swap_mono, lag_bound_s): the staleness pedigree window
        # cached answers are bounded against. Guarded: swap() runs on the
        # round thread, _bound() on whichever listener thread drains.
        self._meta: "OrderedDict[int, Tuple[float, float]]" = OrderedDict()
        self._meta_lock = threading.Lock()
        self._batcher = _Batcher(self._exec_batch, queue_max, self.metrics)

    # -- write side: the round thread ---------------------------------------

    def lag_bound_s(self) -> float:
        """How far behind the fleet's observed writes this worker could
        be right now: max over peers of (age of oldest unapplied delta +
        silence time). 0.0 with no tracker/peers (single-writer truth)."""
        lt = self.lag_tracker
        if lt is None:
            return 0.0
        rep = lt.report()
        return max(
            (r["lag_s"] + r["staleness_s"] for r in rep.values()), default=0.0
        )

    def swap(self, state: Any, seq: int) -> None:
        """Publish-boundary hook: snapshot `state` as the live read
        replica at `seq`, stamped with the current lag bound."""
        resolve = None
        if self.pager is not None and self.pager.has_cold():
            resolve = self.pager.full_state
        snap = self.replica.swap(state, seq, self.lag_bound_s(), resolve=resolve)
        with self._meta_lock:
            self._meta[snap.seq] = (snap.swap_mono, snap.lag_bound_s)
            while len(self._meta) > self.meta_keep:
                self._meta.popitem(last=False)
            horizon = min(self._meta)
        self.cache.purge_below(horizon)

    # -- read side: listener threads ----------------------------------------

    def handle(self, raw: bytes) -> bytes:
        """The one entry point every wire surface calls; response bytes
        are carried verbatim (byte-identical across surfaces)."""
        if faults.ACTIVE:
            faults.fire("serve.query")  # injected stall/raise per surface
        t0 = time.perf_counter()
        self.metrics.count("serve.requests")
        try:
            req = json.loads(bytes(raw).decode("utf-8"))
            queries = req["queries"]
            if not isinstance(queries, list) or not all(
                isinstance(q, dict) for q in queries
            ):
                raise ValueError("queries must be a list of objects")
            ms = req.get("max_staleness_s")
            ms = None if ms is None else float(ms)
        except Exception as e:  # noqa: BLE001 — malformed input degrades
            self.metrics.count("serve.errors")
            return encode({"member": self.member, "error": f"bad request: {e}"})
        try:
            results = self._batcher.run(queries, ms)
        except Overloaded as e:
            return encode({"member": self.member, "error": f"overloaded: {e}"})
        except Exception as e:  # noqa: BLE001 — the batch never hangs a caller
            self.metrics.count("serve.errors")
            return encode({"member": self.member, "error": str(e)})
        self.metrics.merge(
            {"latencies": {"serve.read": [time.perf_counter() - t0]}}
        )
        obs_events.emit("serve.query", n=len(queries), max_staleness_s=ms)
        return encode(
            {"member": self.member, "n": len(results), "results": results}
        )

    def query(
        self,
        queries: List[Dict[str, Any]],
        max_staleness_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """In-process convenience: encode, handle, decode."""
        return json.loads(
            self.handle(request_bytes(queries, max_staleness_s)).decode("utf-8")
        )

    # -- batch execution (single drainer at a time) --------------------------

    def _bound(self, seq: int) -> Optional[float]:
        with self._meta_lock:
            meta = self._meta.get(seq)
        if meta is None:
            return None
        swap_mono, lag_bound = meta
        return (self.mono() - swap_mono) + lag_bound

    def _exec_batch(self, batch: List[_Pending]) -> None:
        nq = sum(len(p.queries) for p in batch)
        self.metrics.count("serve.batches")
        self.metrics.count("serve.queries", nq)
        live = self.replica.live()
        bounds: List[float] = []
        for p in batch:
            p.results = [self._one(q, p.max_staleness, live, bounds)
                         for q in p.queries]
            p.done = True
        if bounds:
            self.metrics.merge({"latencies": {"serve.staleness_bound": bounds}})

    def _one(
        self,
        q: Dict[str, Any],
        ms: Optional[float],
        live: Any,
        bounds: List[float],
    ) -> Dict[str, Any]:
        try:
            kq = kernels.query_key(q)
        except Exception as e:  # noqa: BLE001 — one bad query, one error slot
            self.metrics.count("serve.errors")
            return {"error": f"bad query: {e}"}
        hit = self.cache.get(kq)
        if hit is not None:
            val, seq = hit
            b = self._bound(seq)
            if b is not None:
                b6 = _ceil6(b)
                # No knob: only the live seq's own memo qualifies (reads
                # default to the freshest snapshot). A knob explicitly
                # opts into any cached answer inside the bound.
                ok = (
                    b6 <= ms
                    if ms is not None
                    else (live is None or seq == live.seq)
                )
                if ok:
                    self.metrics.count("serve.cache_hits")
                    bounds.append(b6)
                    return {"value": val, "as_of_seq": seq,
                            "staleness_bound_s": b6}
        # Fall through to the fresh replica.
        if live is None:
            self.metrics.count("serve.errors")
            return {"error": "no snapshot"}
        if live.view is None:
            live.view = kernels.materialize(self.dense, live.state)
        # Bound stamped AFTER materialization: the answer leaves the
        # plane no earlier than this instant, and a bound only ages —
        # stamping before a (possibly compiling) materialize would
        # under-report by its duration.
        b = self._bound(live.seq)
        if b is None:  # pedigree raced out of the window: recompute direct
            b = (self.mono() - live.swap_mono) + live.lag_bound_s
        b6 = _ceil6(b)
        if ms is not None and b6 > ms:
            self.metrics.count("serve.stale_rejects")
            return {"error": "stale", "staleness_bound_s": b6,
                    "max_staleness_s": ms}
        self.metrics.count("serve.cache_misses")
        try:
            val = kernels.answer_one(live.view, q)
        except ValueError as e:
            self.metrics.count("serve.errors")
            return {"error": str(e)}
        self._note_access(q, val)
        self.cache.put(kq, val, live.seq)
        bounds.append(b6)
        return {"value": val, "as_of_seq": live.seq, "staleness_bound_s": b6}

    def _note_access(self, q: Dict[str, Any], val: Any) -> None:
        """Feed the pager's recency clock with the row ids this answer
        touched (topk/range answers are [id, score] pairs; a value query
        names its id directly)."""
        if self.pager is None:
            return
        try:
            ids: List[int] = []
            if q.get("op") == "value" and isinstance(q.get("key"), int):
                ids.append(int(q["key"]))
            if isinstance(val, list):
                ids.extend(
                    int(pair[0]) for pair in val
                    if isinstance(pair, (list, tuple)) and len(pair) >= 1
                )
            if ids:
                self.pager.note_ids(ids)
        except Exception:  # noqa: BLE001 — policy feed only, stay total
            pass

    # -- health --------------------------------------------------------------

    def health_fields(self) -> Dict[str, Any]:
        """Readiness view for /healthz: what seq the replica serves and
        how stale it could be — what an LB needs to drain stale replicas,
        plus the pager's residency picture when paging is on."""
        live = self.replica.live()
        if live is None:
            out: Dict[str, Any] = {
                "serve_seq": -1, "serve_staleness_bound_s": None,
                "serve_cache_entries": len(self.cache),
            }
        else:
            b = self._bound(live.seq)
            if b is None:
                b = (self.mono() - live.swap_mono) + live.lag_bound_s
            out = {
                "serve_seq": live.seq,
                "serve_staleness_bound_s": _ceil6(b),
                "serve_cache_entries": len(self.cache),
            }
        if self.pager is not None:
            out.update(self.pager.health_fields())
        return out
