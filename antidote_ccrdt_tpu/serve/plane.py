"""`ServePlane`: the query front-end every wire surface shares.

One plane per worker ties the pieces together:

* `ReadReplica` — double-buffered device snapshots, swapped at publish
  boundaries by the worker's round thread (`swap`);
* a **bounded batching queue** — concurrent listener threads enqueue
  their decoded queries and one drainer answers the whole accumulated
  batch against a single snapshot materialization (the "one dispatch,
  thousands of queries" shape). Overflow sheds loudly
  (`serve.queue_shed` + an ``overloaded`` error response) instead of
  queueing unboundedly;
* `HotKeyCache` — answers outlive swaps; the `max_staleness_s` request
  knob decides whether an aged entry still qualifies, falls through to
  the fresh replica, or rejects (`serve.stale_rejects`);
* the **staleness contract** — every served value carries
  ``(value, as_of_seq, staleness_bound_s)`` with
  ``bound = (now - swap_mono) + lag_bound_at_swap``, all differences of
  this worker's monotonic clock (skew-immune; rounded UP to the µs so
  formatting can never shave the bound below truth).

Wire surfaces call ONE method — ``handle(request_bytes) ->
response_bytes`` — and transport the bytes verbatim, which is what
makes the tri-surface parity test (`tests/test_serve_parity.py`)
byte-exact: the codec is canonical JSON (sorted keys, compact
separators), so identical questions at identical snapshots produce
identical bytes on the TCP frame, the bridge op, and POST /query.

Request:  ``{"queries": [{"op": "value"|"topk"|"range", "key": int,
            "k"?: int, "lo"?: int, "hi"?: int}, ...],
            "max_staleness_s"?: float,
            "session"?: {origin: seq}}``
Response: ``{"member": str, "n": int, "results": [
            {"value": ..., "as_of_seq": int, "staleness_bound_s": float}
            | {"error": ...}, ...],
            "watermarks": {origin: seq}}``

The ``watermarks`` field is the session-guarantee carrier: the
per-origin applied seqs of the snapshot the answers came from (captured
at swap time from `obs/lag.py`, conservatively the OLDEST snapshot any
result in the batch used). A request's ``session`` token — a
``{origin: seq}`` floor from `serve.session` — is enforced here as the
last line of defense: if the live snapshot's watermarks don't cover the
token, the plane answers ``session_uncovered`` (with its watermarks, so
the router learns how far behind this replica is) rather than serving a
token-violating value. Shed (``overloaded``) responses carry a
``retry_after_ms`` hint derived from current queue depth over the
drain-rate EWMA, and `handle` takes a ``surface`` label ("tcp" /
"bridge" / "http" / ...) so sheds are countable per surface
(``serve.queue_shed.<surface>``) without breaking the byte-identity
contract (the label never enters the response).

`utils.faults` point ``serve.query`` fires at the top of `handle` on
every surface, so injected stalls/raises exercise each listener's own
degrade path (connection close / error frame / HTTP 500 — never a
hang).
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import devprof
from ..obs import events as obs_events
from ..obs import rtrace
from ..utils import faults
from ..utils.metrics import Metrics
from . import kernels
from .cache import HotKeyCache
from .replica import ReadReplica
from .session import gaps as session_gaps


class Overloaded(RuntimeError):
    """The bounded query queue is full; the caller is shed. Carries the
    `retry_after_ms` hint the shed response propagates fleet-wide."""

    def __init__(self, msg: str, retry_after_ms: int = 50):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class SessionUncovered(RuntimeError):
    """The live snapshot's applied watermarks don't cover the request's
    session token — answering would violate the client's session
    guarantee. Carries this replica's watermarks so the router can
    learn and route elsewhere."""

    def __init__(self, msg: str, watermarks: Dict[str, int]):
        super().__init__(msg)
        self.watermarks = dict(watermarks)


def encode(doc: Dict[str, Any]) -> bytes:
    """Canonical response/request bytes: sorted keys, compact
    separators — the tri-surface byte-identity anchor."""
    return (json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n").encode(
        "utf-8"
    )


def request_bytes(
    queries: List[Dict[str, Any]],
    max_staleness_s: Optional[float] = None,
    session: Optional[Dict[str, int]] = None,
    trace: Optional[Dict[str, Any]] = None,
) -> bytes:
    doc: Dict[str, Any] = {"queries": list(queries)}
    if max_staleness_s is not None:
        doc["max_staleness_s"] = float(max_staleness_s)
    if session:
        doc["session"] = {str(o): int(s) for o, s in session.items()}
    if trace:
        # Request-scoped trace context (obs/rtrace.py): rides INSIDE the
        # canonical doc, so every transport carries it opaquely and a
        # legacy peer simply ignores the key.
        doc["trace"] = dict(trace)
    return encode(doc)


def _ceil6(x: float) -> float:
    """Round a staleness bound UP at µs precision — conservative by
    construction (a bound may only ever grow in transit)."""
    return math.ceil(max(0.0, x) * 1e6) / 1e6


class _Pending:
    __slots__ = (
        "queries", "max_staleness", "session", "done", "results", "error",
        "watermarks", "t_enq", "t_drain", "t_done",
    )

    def __init__(
        self,
        queries: List[Dict[str, Any]],
        max_staleness: Optional[float],
        session: Optional[Dict[str, int]] = None,
    ):
        self.queries = queries
        self.max_staleness = max_staleness
        self.session = session
        self.done = False
        self.results: Optional[List[Any]] = None
        self.error: Optional[BaseException] = None
        # The applied-watermark claim for THIS caller's results: the wm
        # of the oldest snapshot any of its answers came from.
        self.watermarks: Optional[Dict[str, int]] = None
        # Stage marks on the plane's mono clock (enqueue -> drain ->
        # done), echoed to traced requests so the client waterfall can
        # split queue_wait from kernel time.
        self.t_enq = 0.0
        self.t_drain = 0.0
        self.t_done = 0.0


class _Batcher:
    """Bounded accumulate-and-drain queue. Any caller thread may become
    the drainer: the first arriver while no drain is running takes the
    whole pending list and answers it in one pass; threads that enqueued
    meanwhile wait on the condition and either find their result ready
    or become the next drainer. No dedicated thread, no idle latency —
    a lone request drains itself immediately, a burst coalesces."""

    def __init__(self, exec_batch: Callable[[List[_Pending]], None],
                 queue_max: int, metrics: Metrics,
                 mono: Callable[[], float] = time.monotonic):
        self._exec = exec_batch
        self.queue_max = max(1, int(queue_max))
        self.metrics = metrics
        self._mono = mono
        self._cv = threading.Condition()
        self._pending: List[_Pending] = []
        self._busy = False
        # Drain-rate EWMA (queries/s) behind the shed retry_after hint.
        self._drain_rate = 0.0

    def retry_after_ms(self, depth: int) -> int:
        """How long a shed caller should wait before retrying: the time
        the current backlog takes to drain at the observed rate, clamped
        to [1ms, 5s]. Before any drain has been timed, a flat 50ms."""
        rate = self._drain_rate
        if rate <= 0.0:
            return 50
        return max(1, min(5000, int(1000.0 * depth / rate)))

    def run(self, queries: List[Dict[str, Any]],
            max_staleness: Optional[float],
            session: Optional[Dict[str, int]] = None) -> _Pending:
        p = _Pending(queries, max_staleness, session)
        p.t_enq = self._mono()
        with self._cv:
            depth = sum(len(x.queries) for x in self._pending)
            if depth + len(queries) > self.queue_max:
                self.metrics.count("serve.queue_shed")
                raise Overloaded(
                    f"query queue full ({depth}+{len(queries)} > {self.queue_max})",
                    retry_after_ms=self.retry_after_ms(depth + len(queries)),
                )
            self._pending.append(p)
            while not p.done and self._busy:
                self._cv.wait(0.05)
            if not p.done:
                self._busy = True
                batch, self._pending = self._pending, []
        if not p.done:
            t0 = time.perf_counter()
            t_drain = self._mono()
            for x in batch:
                x.t_drain = t_drain
            try:
                self._exec(batch)
                t_done = self._mono()
                for x in batch:
                    x.t_done = t_done
                dt = time.perf_counter() - t0
                if dt > 0:
                    inst = sum(len(x.queries) for x in batch) / dt
                    self._drain_rate = (
                        inst if self._drain_rate == 0.0
                        else 0.8 * self._drain_rate + 0.2 * inst
                    )
            finally:
                # A drainer that died mid-batch must not strand followers.
                for x in batch:
                    if not x.done:
                        x.error = x.error or RuntimeError("batch aborted")
                        x.done = True
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()
        if p.error is not None:
            raise p.error
        return p


class ServePlane:
    """One worker's read-serving plane (see module docstring)."""

    def __init__(
        self,
        dense: Any,
        member: str = "?",
        metrics: Optional[Metrics] = None,
        lag_tracker: Any = None,
        mono: Callable[[], float] = time.monotonic,
        cache_cap: int = 1024,
        queue_max: int = 4096,
        meta_keep: int = 8,
        pager: Any = None,
    ):
        self.dense = dense
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.lag_tracker = lag_tracker
        # Out-of-core residency (core/pager.py): swaps resolve the
        # LOGICAL state (device ⊔ cold substrate) so reads never see a
        # demoted partition's hole, and answered row ids feed the
        # pager's recency clock — the serve plane IS the access stream
        # the eviction policy ranks partitions by.
        self.pager = pager
        self.mono = mono  # injectable: frozen in parity tests, virtual in sim
        self.replica = ReadReplica(metrics=self.metrics, mono=mono)
        self.cache = HotKeyCache(cap=cache_cap, metrics=self.metrics)
        self.meta_keep = max(1, int(meta_keep))
        # seq -> (swap_mono, lag_bound_s, watermarks): the staleness +
        # session pedigree window cached answers are bounded against.
        # Guarded: swap() runs on the round thread, _bound() /
        # _watermarks_at() on whichever listener thread drains.
        self._meta: "OrderedDict[int, Tuple[float, float, Dict[str, int]]]" = (
            OrderedDict()
        )
        self._meta_lock = threading.Lock()
        self._batcher = _Batcher(
            self._exec_batch, queue_max, self.metrics, mono=mono
        )

    # -- write side: the round thread ---------------------------------------

    def lag_bound_s(self) -> float:
        """How far behind the fleet's observed writes this worker could
        be right now: max over peers of (age of oldest unapplied delta +
        silence time). 0.0 with no tracker/peers (single-writer truth)."""
        lt = self.lag_tracker
        if lt is None:
            return 0.0
        rep = lt.report()
        return max(
            (r["lag_s"] + r["staleness_s"] for r in rep.values()), default=0.0
        )

    def applied_watermarks(self, seq: int) -> Dict[str, int]:
        """The per-origin applied watermarks a snapshot at `seq` covers:
        this worker's own stream through `seq`, plus — via the lag
        tracker — each peer's stream through what has been applied
        locally. This is the session-token coverage claim responses
        carry."""
        wm: Dict[str, int] = {self.member: int(seq)}
        lt = self.lag_tracker
        if lt is not None:
            for peer, r in lt.report().items():
                wm[str(peer)] = int(r.get("applied", -1))
        return wm

    def swap(self, state: Any, seq: int) -> None:
        """Publish-boundary hook: snapshot `state` as the live read
        replica at `seq`, stamped with the current lag bound and the
        applied watermarks (the session pedigree)."""
        resolve = None
        if self.pager is not None and self.pager.has_cold():
            resolve = self.pager.full_state
        snap = self.replica.swap(state, seq, self.lag_bound_s(), resolve=resolve)
        wm = self.applied_watermarks(snap.seq)
        with self._meta_lock:
            self._meta[snap.seq] = (snap.swap_mono, snap.lag_bound_s, wm)
            while len(self._meta) > self.meta_keep:
                self._meta.popitem(last=False)
            horizon = min(self._meta)
        self.cache.purge_below(horizon)

    # -- read side: listener threads ----------------------------------------

    def handle(self, raw: bytes, surface: str = "local") -> bytes:
        """The one entry point every wire surface calls; response bytes
        are carried verbatim (byte-identical across surfaces — `surface`
        only labels shed metrics, it never enters the response)."""
        if faults.ACTIVE:
            faults.fire("serve.query")  # injected stall/raise per surface
        t0 = time.perf_counter()
        m_in = self.mono()
        ctx = None  # request trace context (obs/rtrace.py), when carried
        self.metrics.count("serve.requests")

        def _echo(doc: Dict[str, Any], p: Optional[_Pending] = None,
                  **extra: Any) -> Dict[str, Any]:
            """Attach the server-side hop timings iff the request was
            traced — an untraced request's response stays byte-identical
            to the pre-trace wire format (tri-surface parity)."""
            if ctx is None:
                return doc
            marks = {"m_in": m_in, "m_out": self.mono()}
            if p is not None:
                marks.update(m_q=p.t_enq, m_drain=p.t_drain,
                             m_done=p.t_done)
            if devprof.ACTIVE:
                # Compile time the device observatory saw inside this
                # hop's window — the kernel bucket's honesty
                # sub-annotation (obs/rtrace.py attribute()).
                cms = devprof.compile_ms_in_window(m_in, marks["m_out"])
                if cms > 0.0:
                    extra.setdefault("compile_ms", cms)
            doc["rtrace"] = rtrace.server_echo(ctx, self.member, marks,
                                               **extra)
            return doc

        try:
            req = json.loads(bytes(raw).decode("utf-8"))
            ctx = rtrace.server_trace(req)
            queries = req["queries"]
            if not isinstance(queries, list) or not all(
                isinstance(q, dict) for q in queries
            ):
                raise ValueError("queries must be a list of objects")
            ms = req.get("max_staleness_s")
            ms = None if ms is None else float(ms)
            sess = req.get("session")
            if sess is not None:
                if not isinstance(sess, dict):
                    raise ValueError("session must be an {origin: seq} object")
                sess = {str(o): int(s) for o, s in sess.items()}
        except Exception as e:  # noqa: BLE001 — malformed input degrades
            self.metrics.count("serve.errors")
            return encode({"member": self.member, "error": f"bad request: {e}"})
        try:
            p = self._batcher.run(queries, ms, sess)
        except Overloaded as e:
            self.metrics.count(f"serve.queue_shed.{surface}")
            return encode(_echo({
                "member": self.member, "error": f"overloaded: {e}",
                "retry_after_ms": e.retry_after_ms,
            }))
        except SessionUncovered as e:
            # Honest refusal: serving would violate the session token.
            # The watermarks tell the router exactly how far behind we
            # are so it can route (or wait) intelligently.
            self.metrics.count("serve.session_uncovered")
            return encode(_echo({
                "member": self.member, "error": f"session_uncovered: {e}",
                "watermarks": e.watermarks,
            }))
        except Exception as e:  # noqa: BLE001 — the batch never hangs a caller
            self.metrics.count("serve.errors")
            return encode(_echo({"member": self.member, "error": str(e)}))
        results = p.results or []
        self.metrics.merge(
            {"latencies": {"serve.read": [time.perf_counter() - t0]}}
        )
        obs_events.emit("serve.query", n=len(queries), max_staleness_s=ms)
        doc: Dict[str, Any] = {
            "member": self.member, "n": len(results), "results": results,
        }
        if p.watermarks is not None:
            doc["watermarks"] = p.watermarks
        return encode(_echo(
            doc, p,
            kernel_ms=round(max(0.0, p.t_done - p.t_drain) * 1e3, 3),
            queued=len(queries),
        ))

    def handler_for(self, surface: str) -> Callable[[bytes], bytes]:
        """A `handle` bound to a surface label — what `install_serve`
        sites register so sheds are attributable per surface."""
        return lambda raw: self.handle(raw, surface=surface)

    def query(
        self,
        queries: List[Dict[str, Any]],
        max_staleness_s: Optional[float] = None,
        session: Optional[Dict[str, int]] = None,
    ) -> Dict[str, Any]:
        """In-process convenience: encode, handle, decode."""
        return json.loads(
            self.handle(
                request_bytes(queries, max_staleness_s, session)
            ).decode("utf-8")
        )

    # -- batch execution (single drainer at a time) --------------------------

    def _bound(self, seq: int) -> Optional[float]:
        with self._meta_lock:
            meta = self._meta.get(seq)
        if meta is None:
            return None
        swap_mono, lag_bound = meta[0], meta[1]
        return (self.mono() - swap_mono) + lag_bound

    def _watermarks_at(self, seq: int) -> Optional[Dict[str, int]]:
        with self._meta_lock:
            meta = self._meta.get(seq)
        return dict(meta[2]) if meta is not None else None

    def _exec_batch(self, batch: List[_Pending]) -> None:
        nq = sum(len(p.queries) for p in batch)
        self.metrics.count("serve.batches")
        self.metrics.count("serve.queries", nq)
        live = self.replica.live()
        live_wm = (
            self._watermarks_at(live.seq) if live is not None else None
        )
        if live_wm is None and live is not None:
            live_wm = self.applied_watermarks(live.seq)
        bounds: List[float] = []
        for p in batch:
            if p.session:
                gp = session_gaps(live_wm or {}, p.session)
                if gp:
                    origin, (have, want) = next(iter(sorted(gp.items())))
                    p.error = SessionUncovered(
                        f"{origin} applied {have} < required {want}",
                        live_wm or {},
                    )
                    p.done = True
                    continue
            seqs: List[int] = []
            p.results = [self._one(q, p.max_staleness, live, bounds, seqs,
                                   p.session)
                         for q in p.queries]
            # The response-level coverage claim must hold for EVERY
            # result, so it is the wm of the OLDEST snapshot used —
            # watermarks are monotone in seq, so that is the pointwise
            # minimum (conservative for the rest).
            if seqs:
                p.watermarks = self._watermarks_at(min(seqs))
                if p.watermarks is None:
                    # Pedigree raced out of the window: claim only what
                    # is true by construction — our own stream.
                    p.watermarks = {self.member: int(min(seqs))}
            elif live_wm is not None:
                p.watermarks = dict(live_wm)
            p.done = True
        if bounds:
            self.metrics.merge({"latencies": {"serve.staleness_bound": bounds}})

    def _one(
        self,
        q: Dict[str, Any],
        ms: Optional[float],
        live: Any,
        bounds: List[float],
        seqs: Optional[List[int]] = None,
        session: Optional[Dict[str, int]] = None,
    ) -> Dict[str, Any]:
        try:
            kq = kernels.query_key(q)
        except Exception as e:  # noqa: BLE001 — one bad query, one error slot
            self.metrics.count("serve.errors")
            return {"error": f"bad query: {e}"}
        hit = self.cache.get(kq)
        if hit is not None:
            val, seq = hit
            b = self._bound(seq)
            if b is not None:
                b6 = _ceil6(b)
                # No knob: only the live seq's own memo qualifies (reads
                # default to the freshest snapshot). A knob explicitly
                # opts into any cached answer inside the bound.
                ok = (
                    b6 <= ms
                    if ms is not None
                    else (live is None or seq == live.seq)
                )
                if ok and session and not (live is None or seq == live.seq):
                    # An aged cached answer must ALSO cover the session
                    # token at ITS OWN snapshot — the batch-level check
                    # only vouched for the live one.
                    wm = self._watermarks_at(seq)
                    ok = wm is not None and not session_gaps(wm, session)
                if ok:
                    self.metrics.count("serve.cache_hits")
                    bounds.append(b6)
                    if seqs is not None:
                        seqs.append(int(seq))
                    return {"value": val, "as_of_seq": seq,
                            "staleness_bound_s": b6}
        # Fall through to the fresh replica.
        if live is None:
            self.metrics.count("serve.errors")
            return {"error": "no snapshot"}
        if live.view is None:
            live.view = kernels.materialize(self.dense, live.state)
        # Bound stamped AFTER materialization: the answer leaves the
        # plane no earlier than this instant, and a bound only ages —
        # stamping before a (possibly compiling) materialize would
        # under-report by its duration.
        b = self._bound(live.seq)
        if b is None:  # pedigree raced out of the window: recompute direct
            b = (self.mono() - live.swap_mono) + live.lag_bound_s
        b6 = _ceil6(b)
        if ms is not None and b6 > ms:
            self.metrics.count("serve.stale_rejects")
            return {"error": "stale", "staleness_bound_s": b6,
                    "max_staleness_s": ms}
        self.metrics.count("serve.cache_misses")
        try:
            val = kernels.answer_one(live.view, q)
        except ValueError as e:
            self.metrics.count("serve.errors")
            return {"error": str(e)}
        self._note_access(q, val)
        self.cache.put(kq, val, live.seq)
        bounds.append(b6)
        if seqs is not None:
            seqs.append(int(live.seq))
        return {"value": val, "as_of_seq": live.seq, "staleness_bound_s": b6}

    def _note_access(self, q: Dict[str, Any], val: Any) -> None:
        """Feed the pager's recency clock with the row ids this answer
        touched (topk/range answers are [id, score] pairs; a value query
        names its id directly)."""
        if self.pager is None:
            return
        try:
            ids: List[int] = []
            if q.get("op") == "value" and isinstance(q.get("key"), int):
                ids.append(int(q["key"]))
            if isinstance(val, list):
                ids.extend(
                    int(pair[0]) for pair in val
                    if isinstance(pair, (list, tuple)) and len(pair) >= 1
                )
            if ids:
                self.pager.note_ids(ids)
        except Exception:  # noqa: BLE001 — policy feed only, stay total
            pass

    # -- health --------------------------------------------------------------

    def health_fields(self) -> Dict[str, Any]:
        """Readiness view for /healthz: what seq the replica serves and
        how stale it could be — what an LB needs to drain stale replicas,
        plus the pager's residency picture when paging is on."""
        live = self.replica.live()
        if live is None:
            out: Dict[str, Any] = {
                "serve_seq": -1, "serve_staleness_bound_s": None,
                "serve_cache_entries": len(self.cache),
            }
        else:
            b = self._bound(live.seq)
            if b is None:
                b = (self.mono() - live.swap_mono) + live.lag_bound_s
            out = {
                "serve_seq": live.seq,
                "serve_staleness_bound_s": _ceil6(b),
                "serve_cache_entries": len(self.cache),
            }
        if self.pager is not None:
            out.update(self.pager.health_fields())
        if devprof.ACTIVE:
            out.update(devprof.health_fields())
        return out
