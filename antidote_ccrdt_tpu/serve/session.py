"""Client session guarantees for the fleet read tier.

A fleet of read replicas converges *eventually*; a single client still
wants two per-session promises on top (the CRDT session-guarantee
taxonomy of arxiv 2310.18220):

* **read-your-writes** — after this session wrote (origin o, seq s), a
  later read must reflect o's stream through s;
* **monotonic-reads** — a later read never observes LESS of any origin's
  stream than an earlier read in the same session did.

Both reduce to one mechanism because every serve response already
carries provenance: the answering replica stamps its response with the
per-origin **applied watermarks** of the snapshot it served from
(`ServePlane.swap` records them from `obs/lag.py`). A session then
carries a `SessionToken` — a per-origin floor `{origin: seq}` — and the
router only accepts answers from replicas whose served watermarks
*cover* the token (`covers`). Writes raise the floor directly
(`note_write`); reads raise it to the served watermarks when
monotonic-reads is on (`note_read`).

The token is plain JSON (`{origin: seq}`), rides the query request under
the ``"session"`` key, and is enforced twice: the router routes only to
peers whose last-known watermarks cover it, and the serving plane
double-checks against the live snapshot (`session_uncovered` error
instead of a silently-stale answer). Every write and every accepted
read is flight-recorded (``session.write`` / ``session.read`` events),
which is what lets `obs.audit.certify_sessions` replay the log and
certify — or produce a counterexample for — the two guarantees after
the fact.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional, Tuple

from ..obs import events as obs_events

_session_ids = itertools.count()


def covers(served: Dict[str, int], token: Dict[str, int]) -> bool:
    """Does a replica's applied-watermark map satisfy a token? Every
    origin the token names must be applied at least through the token's
    floor; an origin the replica has never heard of counts as -1 (it
    cannot prove coverage by silence)."""
    return all(int(served.get(o, -1)) >= int(s) for o, s in token.items())


def gaps(
    served: Dict[str, int], token: Dict[str, int]
) -> Dict[str, Tuple[int, int]]:
    """The uncovered origins: {origin: (have, want)} — empty iff
    `covers`. This is the counterexample shape the audit layer and the
    honest `session_unsatisfiable` error both name."""
    out: Dict[str, Tuple[int, int]] = {}
    for o, want in token.items():
        have = int(served.get(o, -1))
        if have < int(want):
            out[o] = (have, int(want))
    return out


def merge_floor(a: Dict[str, int], b: Dict[str, int]) -> Dict[str, int]:
    """Pointwise max of two per-origin floors (the token join)."""
    out = dict(a)
    for o, s in b.items():
        if int(s) > int(out.get(o, -1)):
            out[o] = int(s)
    return out


class SessionToken:
    """A per-origin seq floor `{origin: seq}`, the wire form of a
    session's accumulated requirement. Thread-safe: router worker
    threads may advance it while the client issues the next read."""

    def __init__(self, floor: Optional[Dict[str, int]] = None):
        self._floor: Dict[str, int] = {
            str(o): int(s) for o, s in (floor or {}).items()
        }
        self._lock = threading.Lock()

    def advance(self, origin: str, seq: int) -> None:
        with self._lock:
            if int(seq) > self._floor.get(origin, -1):
                self._floor[origin] = int(seq)

    def absorb(self, watermarks: Dict[str, int]) -> None:
        """Raise the floor to `watermarks` pointwise (monotonic-reads:
        what one read observed, every later read must re-observe)."""
        with self._lock:
            self._floor = merge_floor(self._floor, watermarks)

    def floor(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._floor)

    def covered_by(self, served: Dict[str, int]) -> bool:
        return covers(served, self.floor())

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._floor)

    def __repr__(self) -> str:
        return f"SessionToken({self.floor()!r})"


class ClientSession:
    """One client's session state + the flight-record feed the audit
    layer certifies from.

    `guarantees` picks which promises the session demands:
    ``read_your_writes`` makes `note_write` raise the token floor;
    ``monotonic_reads`` makes `note_read` absorb served watermarks.
    Either may be disabled to price exactly the contract a caller wants
    (both off = a plain eventually-consistent session whose reads are
    still recorded, so certification stays possible)."""

    def __init__(
        self,
        session_id: Optional[str] = None,
        read_your_writes: bool = True,
        monotonic_reads: bool = True,
    ):
        self.session_id = (
            session_id
            if session_id is not None
            else f"s{next(_session_ids)}"
        )
        self.read_your_writes = bool(read_your_writes)
        self.monotonic_reads = bool(monotonic_reads)
        self.token = SessionToken()

    # -- the client-visible surface -----------------------------------------

    def note_write(self, origin: str, seq: int) -> None:
        """This session observed its own write land as (origin, seq) —
        e.g. the ack of an op it pushed to worker `origin`. Later reads
        must cover it (read-your-writes)."""
        if self.read_your_writes:
            self.token.advance(origin, int(seq))
        # `wseq`, not `seq`: the flight recorder stamps its own per-
        # process `seq` ordinal on every event (same convention as
        # wal.append).
        obs_events.emit(
            "session.write", session=self.session_id, origin=str(origin),
            wseq=int(seq),
        )

    def note_read(
        self, peer: str, served_watermarks: Dict[str, int],
        required: Optional[Dict[str, int]] = None,
    ) -> None:
        """An accepted read answered by `peer` claiming
        `served_watermarks`. Recorded BEFORE the token absorbs the
        watermarks, so the event's `require` field is exactly what this
        read had to satisfy — the replay certifier recomputes the same
        floor independently and cross-checks."""
        obs_events.emit(
            "session.read", session=self.session_id, peer=str(peer),
            require=(required if required is not None else self.token.floor()),
            served={str(o): int(s) for o, s in served_watermarks.items()},
            rw=self.read_your_writes, mono=self.monotonic_reads,
        )
        if self.monotonic_reads:
            self.token.absorb(served_watermarks)

    def requirement(self) -> Dict[str, int]:
        """The floor a read issued NOW must satisfy."""
        return self.token.floor()


def session_doc(token: Any) -> Optional[Dict[str, int]]:
    """Normalize a token (SessionToken | dict | None) to its wire dict,
    None when empty — request encoders call this so an empty session
    adds no bytes to the frame."""
    if token is None:
        return None
    floor = token.floor() if isinstance(token, SessionToken) else dict(token)
    return {str(o): int(s) for o, s in floor.items()} or None
