"""SWIM-style membership: piggybacked heartbeats, suspect -> confirm-dead.

The failure-detector shape follows SWIM (Das et al.): liveness evidence
rides on the frames members already exchange (every snapshot/delta/ping
frame carries the sender's last-heard AGES for everyone it knows), so
detection latency is bounded by gossip traffic rather than by a separate
ping schedule, and evidence is TRANSITIVE — A can keep B alive in C's
view while C's direct link to B is down. A silent member degrades
through SUSPECT (still owns its replicas; transient stalls — GC pauses,
one dropped link — don't flap ownership) before CONFIRM-DEAD removes it
from the alive set that feeds `parallel.elastic.owners`.

Two deliberate simplifications vs full SWIM, safe because the consumer
is idempotent gossip rather than a routed overlay: no indirect
ping-req round (piggybacked ages already provide the indirection), and
no incarnation-number refutation (a falsely-suspected member's next
frame re-alives it; brief ownership overlap is harmless — the join
dedups, as documented in `parallel.elastic.owners`).

Ages (not timestamps) go on the wire, so members never need synchronized
clocks; each member timestamps evidence against its own monotonic `now`.
The clock source is injected — `net.sim` drives this class with a
virtual clock for deterministic chaos replay.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..obs import events as obs_events
from ..utils.metrics import Metrics

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


class Membership:
    """Last-heard tracking + the SWIM state machine.

    `timeout_s` (passed per query, matching the `alive_members` surface
    the gossip tier already speaks) is the ALIVE horizon; a member goes
    SUSPECT past it and DEAD past ``confirm_factor * timeout_s``.
    SUSPECT members still count as alive for replica ownership — only
    confirm-dead shifts the `owners()` map."""

    def __init__(
        self,
        member: str,
        now: Callable[[], float] = time.monotonic,
        confirm_factor: float = 2.0,
        metrics: Optional[Metrics] = None,
    ):
        self.member = member
        self.now = now
        self.confirm_factor = confirm_factor
        self.metrics = metrics if metrics is not None else Metrics()
        self.last_heard: Dict[str, float] = {member: now()}
        # Members currently flagged suspect/dead, for edge-triggered
        # metrics (count transitions, not polls).
        self._suspected: set = set()
        self._dead: set = set()

    # -- evidence ----------------------------------------------------------

    def observe(self, member: str, age: float = 0.0) -> None:
        """Record evidence that `member` was alive `age` seconds ago
        (0 = we just heard from it directly). Stale evidence (older than
        what we already hold) is ignored; fresh evidence clears suspicion
        — the SWIM re-alive transition."""
        t = self.now() - age
        if t > self.last_heard.get(member, float("-inf")):
            self.last_heard[member] = t
            if member in self._suspected or member in self._dead:
                # Only a *recent* sighting refutes: letting any newer-but-
                # still-ancient gossip clear the flags would re-alive a
                # confirmed-dead member on every piggyback exchange.
                was = DEAD if member in self._dead else SUSPECT
                self._suspected.discard(member)
                self._dead.discard(member)
                obs_events.emit(
                    "peer.realive", peer=member, was=was, age=round(age, 6)
                )

    def heard_ages(self) -> Dict[str, float]:
        """Piggyback payload: member -> seconds since last heard (self is
        always 0). Receivers feed this to `absorb`."""
        now = self.now()
        out = {m: now - t for m, t in self.last_heard.items()}
        out[self.member] = 0.0
        return out

    def absorb(self, ages: Dict[str, float]) -> None:
        """Merge a peer's piggybacked `heard_ages` (transitive liveness)."""
        for m, age in ages.items():
            self.observe(m, age=float(age))

    # -- classification ----------------------------------------------------

    def state_of(self, member: str, timeout_s: float) -> str:
        if member == self.member:
            return ALIVE
        t = self.last_heard.get(member)
        if t is None:
            return DEAD
        age = self.now() - t
        if age <= timeout_s:
            return ALIVE
        if age <= self.confirm_factor * timeout_s:
            if member not in self._suspected:
                self._suspected.add(member)
                self.metrics.count("net.suspect_events")
                # Edge-triggered like the counter, but carrying the
                # evidence: the heartbeat age that crossed the horizon.
                obs_events.emit(
                    "peer.suspect",
                    peer=member,
                    age=round(age, 6),
                    timeout_s=timeout_s,
                )
            return SUSPECT
        if member not in self._dead:
            self._dead.add(member)
            self._suspected.discard(member)
            self.metrics.count("net.dead_events")
            obs_events.emit(
                "peer.dead",
                peer=member,
                age=round(age, 6),
                timeout_s=timeout_s,
            )
        return DEAD

    def members(self) -> List[str]:
        """Everyone ever heard of (including self, including the dead)."""
        return sorted(self.last_heard)

    def alive(self, timeout_s: float) -> List[str]:
        """The ownership-feeding alive set: ALIVE + SUSPECT members (a
        suspect keeps its replicas until confirmed dead). Self is always
        included — a member never suspects itself."""
        return sorted(
            m for m in self.last_heard if self.state_of(m, timeout_s) != DEAD
        )
