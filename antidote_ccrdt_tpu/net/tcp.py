"""Real TCP gossip peer: push-replicated blobs over `{packet,4}` frames.

Wire: the bridge's framing (`bridge.protocol.pack_frame`/`unpack_frames`
— u32_be length + ETF payload), so a BEAM host could join the gossip
mesh natively. Frame terms (member names as utf-8 binaries, `heard` the
sender's piggybacked `Membership.heard_ages` map):

    {snap,  Member, Blob, Heard}
    {delta, Member, Seq, Keep, Blob, Heard}
    {ping,  Member, Heard}
    {metrics_req}                      -> {metrics_resp, Member, Text}
    {metrics_req, T1}                  -> {metrics_resp, Member, Text, T1, T2}

Clock piggyback (obs/spans.py): `{hello}` may carry a 5th element — the
sender's `time.monotonic()` at send (T1) — and the matching
`{hello_ack}` then carries (T1, T2) where T2 is the receiver's
monotonic clock at receipt; likewise `{metrics_req, T1}`. At the reply
the sender computes the NTP-style estimate ``offset = T2 - (T1+T3)/2``
(T3 = reply receipt) and feeds `obs.spans.ClockSync`, which is how a
fleet's span timelines align. Both handlers index tolerantly, so mixed
old/new fleets interop: short tuples mean "no clock data".

`metrics_req` is the one request/reply pair: a scraper (Prometheus shim,
`scrape_metrics`, the dashboard) connects, sends the request, and gets
this member's OpenMetrics text back on the SAME inbound connection — the
only frame ever written back on an accepted socket. Scrapers are not
members: the request bypasses membership observation entirely.

Topology: full mesh over a static address book by default. Each member
keeps ONE outgoing connection per peer (`_PeerLink`) feeding from a
bounded send queue; inbound connections are accept-and-read only.
Received blobs land in local caches, so the `Transport` fetch surface is
a local dict read — anti-entropy stays pull-shaped above (`sweep_deltas`
chains whatever has arrived) while the medium is push-shaped below.

`install_router()` switches the mesh to the zone-aware topology from
`topo/`: frames then go where `ZoneRouter.send_targets` says (leaves
intra-zone, anchors also to remote-zone anchors), cross-zone frames
travel as `{rsnap,...}`/`{rdelta,...}` carrying (member, zone) hop
stamps, and receiving anchors relay per `plan_relay` — each relayed
send shows up as a `frame.relay` event and in the
`topo.cross_zone.{frames,bytes}` counters. Links also negotiate a codec
at connect time via `{hello}`/`{hello_ack}` (codec byte 0=raw 1=zlib
ahead of the ETF payload, `topo.codec`); a peer that never acks —
an un-upgraded build — gets legacy bare-ETF frames forever, so mixed
fleets interop. The default compress policy is zlib on cross-zone links
only (`compress="cross_zone"`): intra-zone links are cheap, the DCN is
not.

Failure behavior (the design goal: DEGRADE, never hang):

* connects/sends carry timeouts; a stalled peer costs the sender thread,
  never the caller;
* reconnects retry forever with exponential backoff + jitter (metrics:
  `net.retries`) — a dead peer is cheap to keep trying;
* the send queue is bounded with a drop-oldest-delta-keep-anchor policy:
  deltas are join-decomposed (`parallel.delta`), so a dropped delta only
  breaks the receiver's chain, and the periodically-published full
  anchor resyncs the gap (`sweep_deltas`'s fallback). Snapshots are
  latest-wins — a newly queued anchor replaces any queued older one;
* liveness comes from `net.membership` fed by every received frame, so
  a stalled peer decays ALIVE -> SUSPECT -> DEAD instead of blocking.

Frames are ENCODED AT SEND TIME (the queue holds builders, not bytes) so
piggybacked ages are measured when the frame actually leaves — a frame
that sat queued behind a dead link must not deliver stale "I heard X
recently" claims.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..bridge.protocol import pack_frame, unpack_frames
from ..core import etf
from ..core.etf import Atom
from ..obs import events as obs_events
from ..obs import spans as obs_spans
from ..topo import (
    CODEC_RAW,
    CODEC_ZLIB,
    UNKNOWN_ZONE,
    ZoneMap,
    ZoneRouter,
    encode_frame,
    unpack_coded_frames,
    zone_from_env,
)
from ..utils import faults
from . import transport
from ..utils.metrics import Metrics
from .membership import Membership

A_SNAP = Atom("snap")
A_DELTA = Atom("delta")
A_PING = Atom("ping")
A_METRICS_REQ = Atom("metrics_req")
A_METRICS_RESP = Atom("metrics_resp")
A_HELLO = Atom("hello")
A_HELLO_ACK = Atom("hello_ack")
A_RSNAP = Atom("rsnap")
A_RDELTA = Atom("rdelta")
A_DIG = Atom("dig")
A_RDIG = Atom("rdig")
A_PSNAP = Atom("psnap")
A_PSNAP_REQ = Atom("psnap_req")
A_QUERY = Atom("query")
A_QUERY_RESP = Atom("query_resp")
A_WRITE = Atom("write")
A_WRITE_ACK = Atom("write_ack")

_SNAP, _DELTA, _PING, _DIG, _PSNAP = "snap", "delta", "ping", "dig", "psnap"

# (member, zone) hop stamps of a routed frame, origin first.
_Path = List[Tuple[str, str]]


def scrape_metrics(addr: Tuple[str, int], timeout: float = 2.0) -> Tuple[str, str]:
    """One-shot in-band scrape of a live `TcpTransport`: connect to its
    gossip listener, send `{metrics_req}`, return (member, OpenMetrics
    text). Bounded by `timeout` end-to-end — a wedged or fault-injected
    worker yields `socket.timeout`/`ConnectionError`, never a hang."""
    deadline = time.monotonic() + timeout
    with socket.create_connection(addr, timeout=timeout) as s:
        t1 = time.monotonic()
        s.sendall(pack_frame((A_METRICS_REQ, t1)))
        buf = bytearray()
        while True:
            s.settimeout(max(0.01, deadline - time.monotonic()))
            data = s.recv(1 << 16)
            if not data:
                raise ConnectionError("scrape connection closed before reply")
            buf.extend(data)
            for term in unpack_frames(buf):
                if term[0] == A_METRICS_RESP:
                    member = term[1].decode("utf-8")
                    if len(term) >= 5:
                        # Echoed (T1, T2): a scraper running the span
                        # plane refines its offset to this worker.
                        obs_spans.observe_exchange(
                            member,
                            float(term[3]),
                            float(term[4]),
                            time.monotonic(),
                        )
                    return member, term[2].decode("utf-8")


class QueryCancelled(ConnectionError):
    """`query_peer` abandoned because its `cancel` event was set — the
    router reaped a hedge loser or failed over off this peer."""


def query_peer(
    addr: Tuple[str, int],
    payload: bytes,
    timeout: float = 2.0,
    cancel: Optional[threading.Event] = None,
    connect_timeout: Optional[float] = None,
    qid: Optional[bytes] = None,
) -> Tuple[str, bytes]:
    """One-shot serve-plane read against a live `TcpTransport`: connect
    to its gossip listener, send `{query, Payload[, Qid]}`, return
    (member, response bytes — the serve plane's canonical JSON,
    verbatim). Bounded by `timeout` end-to-end: the deadline is checked
    explicitly on EVERY loop turn, so a peer that accepts the frame and
    then drips unrelated traffic (or nothing) without ever answering
    still surfaces `socket.timeout` — connection-level faults are not
    the only escape hatch. The fleet router leans on this: a
    never-answering peer must time out so it can fail over instead of
    hanging. `cancel` (a threading.Event) aborts the wait early with
    `QueryCancelled` — how a hedged/failed-over attempt's loser is
    reaped. `qid` is opaque router metadata echoed back in the response
    frame (correlation under failover). The querier never joins the
    gossip membership."""
    deadline = time.monotonic() + timeout
    frame: Tuple[Any, ...] = (
        (A_QUERY, bytes(payload)) if qid is None
        else (A_QUERY, bytes(payload), bytes(qid))
    )
    with socket.create_connection(
        addr, timeout=(connect_timeout if connect_timeout is not None
                       else timeout)
    ) as s:
        s.sendall(pack_frame(frame))
        buf = bytearray()
        while True:
            now = time.monotonic()
            if now >= deadline:
                raise socket.timeout(
                    f"query deadline exceeded ({timeout}s, no query_resp)"
                )
            if cancel is not None and cancel.is_set():
                raise QueryCancelled("query cancelled by router")
            # Short recv slices so cancellation and the hard deadline
            # are both honored even while frames keep trickling in.
            s.settimeout(max(0.01, min(0.1, deadline - now)))
            try:
                data = s.recv(1 << 16)
            except socket.timeout:
                continue  # no bytes this slice; deadline check re-arms
            if not data:
                raise ConnectionError("query connection closed before reply")
            buf.extend(data)
            for term in unpack_frames(buf):
                if term[0] == A_QUERY_RESP:
                    if qid is not None and (
                        len(term) < 4 or bytes(term[3]) != bytes(qid)
                    ):
                        continue  # someone else's (stale) answer
                    return term[1].decode("utf-8"), bytes(term[2])


def write_peer(
    addr: Tuple[str, int],
    payload: bytes,
    timeout: float = 2.0,
    cancel: Optional[threading.Event] = None,
    connect_timeout: Optional[float] = None,
    wid: Optional[bytes] = None,
) -> Tuple[str, bytes]:
    """One-shot ingest-plane write against a live `TcpTransport`: send
    `{write, Payload[, Wid]}`, return (member, ack bytes — the ingest
    plane's canonical JSON, verbatim). The SAME deadline/cancel
    contract as `query_peer`: the deadline is checked on every loop
    turn so a peer that accepts the frame and never acks surfaces
    `socket.timeout` (the write router fails over — safely, because the
    payload's write_id dedups at the successor), and `cancel` aborts
    with `QueryCancelled`. `wid` is opaque router correlation metadata
    echoed in the ack frame. The writer never joins the membership."""
    deadline = time.monotonic() + timeout
    frame: Tuple[Any, ...] = (
        (A_WRITE, bytes(payload)) if wid is None
        else (A_WRITE, bytes(payload), bytes(wid))
    )
    with socket.create_connection(
        addr, timeout=(connect_timeout if connect_timeout is not None
                       else timeout)
    ) as s:
        s.sendall(pack_frame(frame))
        buf = bytearray()
        while True:
            now = time.monotonic()
            if now >= deadline:
                raise socket.timeout(
                    f"write deadline exceeded ({timeout}s, no write_ack)"
                )
            if cancel is not None and cancel.is_set():
                raise QueryCancelled("write cancelled by router")
            s.settimeout(max(0.01, min(0.1, deadline - now)))
            try:
                data = s.recv(1 << 16)
            except socket.timeout:
                continue  # no bytes this slice; deadline check re-arms
            if not data:
                raise ConnectionError("write connection closed before ack")
            buf.extend(data)
            for term in unpack_frames(buf):
                if term[0] == A_WRITE_ACK:
                    if wid is not None and (
                        len(term) < 4 or bytes(term[3]) != bytes(wid)
                    ):
                        continue  # someone else's (stale) ack
                    return term[1].decode("utf-8"), bytes(term[2])


def probe_clock(
    addr: Tuple[str, int], timeout: float = 2.0
) -> Tuple[str, float, float]:
    """One NTP-style exchange against a live worker over the in-band
    `{metrics_req, T1}` frame: returns (member, offset, rtt) where
    ``offset ~= worker_monotonic - local_monotonic``. Raises like
    `scrape_metrics` on a dead/legacy worker (a 3-element reply means
    the peer predates the clock piggyback)."""
    deadline = time.monotonic() + timeout
    with socket.create_connection(addr, timeout=timeout) as s:
        t1 = time.monotonic()
        s.sendall(pack_frame((A_METRICS_REQ, t1)))
        buf = bytearray()
        while True:
            s.settimeout(max(0.01, deadline - time.monotonic()))
            data = s.recv(1 << 16)
            if not data:
                raise ConnectionError("probe connection closed before reply")
            buf.extend(data)
            for term in unpack_frames(buf):
                if term[0] == A_METRICS_RESP:
                    if len(term) < 5:
                        raise ConnectionError(
                            "peer replied without clock echo (legacy build)"
                        )
                    t3 = time.monotonic()
                    t2 = float(term[4])
                    member = term[1].decode("utf-8")
                    obs_spans.observe_exchange(member, t1, t2, t3)
                    return member, t2 - (t1 + t3) / 2.0, t3 - t1


class _PeerLink:
    """One outgoing connection: bounded queue + sender thread with
    backoff. `enqueue` never blocks the caller; the queue policy keeps
    at most one snapshot (latest anchor) and one pending ping, and sheds
    the OLDEST delta first when full."""

    def __init__(
        self,
        name: str,
        addr: Tuple[str, int],
        rng: random.Random,
        metrics: Metrics,
        queue_max: int,
        connect_timeout: float,
        send_timeout: float,
        backoff_base: float,
        backoff_max: float,
        negotiate: Optional[Callable[[socket.socket], Optional[int]]] = None,
    ):
        self.name = name  # peer's member name (frame.send events, gauges)
        self.addr = addr
        self.rng = rng
        self.metrics = metrics
        self.queue_max = queue_max
        self.connect_timeout = connect_timeout
        self.send_timeout = send_timeout
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        # Hello exchange run on each fresh socket; returns the codec the
        # peer accepts, or None for a legacy peer (bare-ETF frames).
        # Re-runs on every reconnect — the peer may have been upgraded.
        self.negotiate = negotiate
        self.codec: Optional[int] = None
        # (kind, build_frame: () -> bytes, meta: trace context carried to
        # the frame.send event — {origin, dseq} for deltas)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._sock: Optional[socket.socket] = None
        self._attempts = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _gauge_depth(self) -> None:
        # Called under self._cv: per-peer send-queue depth for the
        # dashboard (a climbing gauge = this peer's link is stalling).
        self.metrics.set(f"net.sendq.{self.name}", float(len(self._q)))

    def enqueue(
        self,
        kind: str,
        build_frame: Callable[[], bytes],
        meta: Optional[Dict[str, object]] = None,
    ) -> None:
        with self._cv:
            if self._stop:
                return
            if kind in (_SNAP, _DIG):
                # Latest-wins: a queued older snapshot/digest is dead weight.
                stale = [i for i, (k, _, _m) in enumerate(self._q) if k == kind]
                for i in reversed(stale):
                    del self._q[i]
            elif kind == _PING and any(k == _PING for k, _, _m in self._q):
                return  # one pending ping is enough liveness signal
            if len(self._q) >= self.queue_max:
                # Backpressure: shed the oldest DELTA (anchors resync the
                # gap); only if no delta is queued shed the oldest frame.
                for i, (k, _, _m) in enumerate(self._q):
                    if k == _DELTA:
                        del self._q[i]
                        break
                else:
                    self._q.popleft()
                self.metrics.count("net.send_drops")
            self._q.append((kind, build_frame, meta or {}))
            self._gauge_depth()
            self._cv.notify()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)

    # -- sender thread -----------------------------------------------------

    def _backoff(self) -> float:
        d = min(self.backoff_max, self.backoff_base * (2.0 ** self._attempts))
        return d * (0.5 + self.rng.random())  # jitter in [0.5d, 1.5d)

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        try:
            s = socket.create_connection(self.addr, timeout=self.connect_timeout)
            s.settimeout(self.send_timeout)
            if self.negotiate is not None:
                try:
                    self.codec = self.negotiate(s)
                except Exception:
                    self.codec = None  # any hello trouble -> legacy frames
            self._sock = s
            self._attempts = 0
            self.metrics.count("net.connects")
            return True
        except OSError:
            self._attempts += 1
            self.metrics.count("net.retries")
            return False

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                kind, build, meta = self._q[0]
            if not self._ensure_connected():
                with self._cv:
                    self._cv.wait(timeout=self._backoff())
                    if self._stop:
                        return
                continue
            # Wire-time span on the SENDER thread: attribution counts it
            # as overlappable — the worker round never waited for it.
            tok = (
                obs_spans.begin(
                    "round.gossip_send", wire=True, peer=self.name,
                    fkind=kind,
                    **{k: meta[k] for k in ("origin", "dseq") if k in meta},
                )
                if obs_spans.ACTIVE
                else None
            )
            try:
                frame = build()
                dropped = False
                try:
                    # Fault point `tcp.send`: raise = connection reset
                    # mid-send (exercises the reconnect/backoff path
                    # exactly like a real ECONNRESET); drop = frame lost
                    # on the wire (the queue treats it as sent —
                    # receivers resync via anchors).
                    if faults.ACTIVE and faults.fire("tcp.send") == "drop":
                        dropped = True
                        self.metrics.count("net.fault_drops")
                    else:
                        self._sock.sendall(frame)
                except OSError:
                    # close() may have nulled _sock concurrently (it owns
                    # the socket teardown); swap-then-close so both
                    # orders are safe.
                    s, self._sock = self._sock, None
                    if s is not None:
                        try:
                            s.close()
                        except OSError:
                            pass
                    self._attempts += 1
                    self.metrics.count("net.retries")
                    continue  # same frame retries after reconnect
            finally:
                obs_spans.end(tok)
            with self._cv:
                # Sent: drop it (the queue head may have been reshuffled
                # by the snap-replacement policy; remove by identity).
                try:
                    self._q.remove((kind, build, meta))
                except ValueError:
                    pass
                self._gauge_depth()
            if not dropped:
                self.metrics.count("net.frames_sent")
                self.metrics.count("net.bytes_sent", len(frame))
                if meta.get("cross_zone"):
                    # Counted at actual wire time with post-codec sizes:
                    # these two gauges ARE the DCN bill the topology is
                    # meant to shrink (bench_gate reports them).
                    self.metrics.count("topo.cross_zone.frames")
                    self.metrics.count("topo.cross_zone.bytes", len(frame))
                # Emitted when the frame actually left (not at enqueue):
                # delta metas carry (origin, dseq) so the trace shows the
                # true wire time of each propagation hop.
                obs_events.emit(
                    "frame.send",
                    peer=self.name,
                    fkind=kind,
                    bytes=len(frame),
                    **meta,
                )


class TcpTransport:
    """`net.transport.Transport` over real sockets (see module docstring).

    `peers` is the static address book {member: (host, port)}; `bind`
    may use port 0 (the kernel-assigned address is `self.address`, for
    rendezvous schemes like the demo's address files). `members()`
    reports only members actually HEARD FROM (self included) — the
    address book is connectivity, membership is evidence — so start
    barriers wait for real traffic, exactly like heartbeat files."""

    def __init__(
        self,
        member: str,
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        peers: Optional[Dict[str, Tuple[str, int]]] = None,
        metrics: Optional[Metrics] = None,
        queue_max: int = 64,
        connect_timeout: float = 0.5,
        send_timeout: float = 2.0,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        seed: Optional[int] = None,
        zone: Optional[str] = None,
        compress: str = "cross_zone",
        hello_timeout: float = 1.0,
    ):
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.membership = Membership(member, metrics=self.metrics)
        # Zone defaults to CCRDT_ZONE (one shared default zone when unset,
        # so unconfigured fleets keep exact full-mesh behavior). Routing
        # stays full-mesh until install_router() is called.
        self.zone = zone if zone is not None else zone_from_env()
        self.zones = ZoneMap(member, self.zone)
        self.router: Optional[ZoneRouter] = None
        self.compress = compress  # "off" | "cross_zone" | "all"
        self.hello_timeout = hello_timeout
        self._rng = random.Random(
            seed if seed is not None else hash(member) & 0xFFFFFFFF
        )
        self._lock = threading.Lock()
        self._snaps: Dict[str, bytes] = {}
        self._deltas: Dict[str, Dict[int, bytes]] = {}
        # Partition plane: per-member digest-vector blobs (pushed, tiny)
        # and per-(member, part) psnap blobs. Own psnaps are STORED here
        # at anchor time and only cross the wire when a peer requests
        # divergent partitions ({psnap_req} -> {psnap}).
        self._digs: Dict[str, bytes] = {}
        self._psnaps: Dict[str, Dict[int, bytes]] = {}
        # Serve plane: `{query, Payload}` frames are answered by this
        # handler (bytes -> bytes) when a plane is installed; None means
        # this worker does not serve reads (error reply, never a hang).
        self.query_handler: Optional[Callable[[bytes], bytes]] = None
        self.write_handler: Optional[Callable[[bytes], bytes]] = None
        self._closed = False

        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(bind)
        self._server.listen(16)
        self.address: Tuple[str, int] = self._server.getsockname()[:2]

        self._link_params = (
            queue_max, connect_timeout, send_timeout, backoff_base, backoff_max,
        )
        self._links: Dict[str, _PeerLink] = {}
        for name, addr in sorted((peers or {}).items()):
            self.add_peer(name, addr)

        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()

    def add_peer(self, name: str, addr: Tuple[str, int]) -> None:
        """Open (or keep) the outgoing link to `name`. Exists because
        port-0 binds can't know each other's addresses at construction —
        rendezvous (the demo's addr files) discovers them afterwards."""
        if name == self.member:
            return
        with self._lock:
            if name in self._links or self._closed:
                return
            self._links[name] = _PeerLink(
                name, tuple(addr), self._rng, self.metrics,
                *self._link_params, negotiate=self._hello_exchange,
            )

    def install_serve(self, plane: Any) -> None:
        """Attach a serve plane (or any bytes->bytes handler): inbound
        `{query, Payload}` frames are answered with `{query_resp,
        Member, ResponseBytes}` on the same connection. A real
        `ServePlane` gets its "tcp"-labelled handler so sheds on this
        surface are countable apart from bridge/HTTP ones. Payload is
        opaque: an rtrace context (``"trace"`` in the canonical JSON
        doc) and the response-borne ``"rtrace"`` echo ride these frames
        byte-for-byte with no frame-format change."""
        handler_for = getattr(plane, "handler_for", None)
        if callable(handler_for):
            self.query_handler = handler_for("tcp")
        else:
            self.query_handler = getattr(plane, "handle", plane)

    def install_ingest(self, plane: Any) -> None:
        """Attach an ingest plane (or any bytes->bytes handler): inbound
        `{write, Payload}` frames are answered with `{write_ack, Member,
        AckBytes}` on the same connection — the write tier's twin of
        `install_serve`. A real `IngestPlane` gets its "tcp"-labelled
        handler so write sheds on this surface count separately. Like
        the query frames, the payload (bare JSON or a CCRF range frame)
        is opaque — a ``"trace"`` context inside it and the ack's
        ``"rtrace"`` echo propagate unchanged."""
        handler_for = getattr(plane, "handler_for", None)
        if callable(handler_for):
            self.write_handler = handler_for("tcp")
        else:
            self.write_handler = getattr(plane, "handle", plane)

    def learn_zone(self, name: str, zone: str) -> None:
        """Feed static zone config (address files, CLI) into the map —
        hellos and relay stamps keep teaching it afterwards."""
        self.zones.learn(name, zone)

    def install_router(self, timeout_s: float = 2.0) -> ZoneRouter:
        """Switch from full-mesh to the zone-aware topology (`topo/`).
        `timeout_s` is the SWIM alive-horizon anchor elections use.
        Peers with unknown zones keep full-mesh treatment, so calling
        this before zones are learned only delays the traffic win."""
        self.router = ZoneRouter(
            self.member,
            self.zone,
            self.zones,
            membership=self.membership,
            timeout_s=timeout_s,
            metrics=self.metrics,
        )
        return self.router

    # -- per-link codec negotiation ----------------------------------------

    def _hello_exchange(self, sock: socket.socket) -> Optional[int]:
        """Run on the sender thread right after each connect: send
        `{hello, Member, Zone, [Codecs]}` (legacy-framed — an old peer
        decodes it as an unknown tag and ignores it) and wait, bounded,
        for `{hello_ack, Member, Zone, Codec}` on the same socket — the
        second of the two write-back frames inbound handlers may send.
        Timeout/EOF/garbage all mean "legacy peer": frames to this link
        stay bare ETF. The ack also teaches us the peer's zone."""
        try:
            t1 = time.monotonic()
            sock.sendall(
                pack_frame((
                    A_HELLO,
                    self.member.encode("utf-8"),
                    self.zone.encode("utf-8"),
                    [CODEC_RAW, CODEC_ZLIB],
                    t1,  # clock piggyback; old peers ignore the extra slot
                ))
            )
            self.metrics.count("net.hellos")
            buf = bytearray()
            deadline = time.monotonic() + self.hello_timeout
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                sock.settimeout(left)
                data = sock.recv(1 << 16)
                if not data:
                    return None
                buf.extend(data)
                for term in unpack_coded_frames(buf):
                    if term[0] == A_HELLO_ACK:
                        # Index tolerantly: a legacy ack is 4 elements, a
                        # clock-bearing one appends (T1, T2).
                        mb, zb, codec = term[1], term[2], term[3]
                        peer = mb.decode("utf-8")
                        self.zones.learn(peer, zb.decode("utf-8"))
                        if len(term) >= 6:
                            obs_spans.observe_exchange(
                                peer,
                                float(term[4]),
                                float(term[5]),
                                time.monotonic(),
                            )
                        self.metrics.count("net.hello_acks")
                        return int(codec)
        except (OSError, ValueError):
            return None
        finally:
            try:
                sock.settimeout(self._link_params[2])  # send_timeout
            except OSError:
                pass

    def _link_codec(self, link: _PeerLink) -> Optional[int]:
        """Effective send codec for one link, decided at build time:
        min(what the peer accepts, what the compress policy wants)."""
        negotiated = link.codec
        if negotiated is None:
            return None  # legacy peer
        if negotiated >= CODEC_ZLIB and self._compress_to(link.name):
            return CODEC_ZLIB
        return CODEC_RAW

    def _compress_to(self, peer: str) -> bool:
        if self.compress == "all":
            return True
        if self.compress == "off":
            return False
        pz = self.zones.zone_of(peer)
        return pz not in (self.zone, UNKNOWN_ZONE)

    # -- frame builders (called at send time, see module docstring) --------

    def _wire(self, term, link: _PeerLink) -> bytes:
        codec = self._link_codec(link)
        if codec is None:
            return pack_frame(term)
        return encode_frame(etf.encode(term), codec, self.metrics)

    def _heard_term(self) -> Dict[bytes, float]:
        return {
            m.encode("utf-8"): float(age)
            for m, age in self.membership.heard_ages().items()
        }

    def _snap_frame(self, blob: bytes, link: _PeerLink) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: self._wire((A_SNAP, mb, blob, self._heard_term()), link)

    def _delta_frame(
        self, seq: int, keep: int, blob: bytes, link: _PeerLink
    ) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: self._wire(
            (A_DELTA, mb, seq, keep, blob, self._heard_term()), link
        )

    def _ping_frame(self, link: _PeerLink) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: self._wire((A_PING, mb, self._heard_term()), link)

    @staticmethod
    def _path_term(path: _Path) -> List[Tuple[bytes, bytes]]:
        return [(m.encode("utf-8"), z.encode("utf-8")) for m, z in path]

    def _rsnap_frame(
        self, origin: str, blob: bytes, path: _Path, link: _PeerLink
    ) -> Callable[[], bytes]:
        ob, pt = origin.encode("utf-8"), self._path_term(path)
        return lambda: self._wire(
            (A_RSNAP, ob, blob, pt, self._heard_term()), link
        )

    def _rdelta_frame(
        self,
        origin: str,
        seq: int,
        keep: int,
        blob: bytes,
        path: _Path,
        link: _PeerLink,
    ) -> Callable[[], bytes]:
        ob, pt = origin.encode("utf-8"), self._path_term(path)
        return lambda: self._wire(
            (A_RDELTA, ob, seq, keep, blob, pt, self._heard_term()), link
        )

    def _dig_frame(self, blob: bytes, link: _PeerLink) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: self._wire((A_DIG, mb, blob, self._heard_term()), link)

    def _rdig_frame(
        self, origin: str, blob: bytes, path: _Path, link: _PeerLink
    ) -> Callable[[], bytes]:
        ob, pt = origin.encode("utf-8"), self._path_term(path)
        return lambda: self._wire(
            (A_RDIG, ob, blob, pt, self._heard_term()), link
        )

    def _psnap_frame(
        self, part: int, blob: bytes, link: _PeerLink
    ) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: self._wire(
            (A_PSNAP, mb, part, blob, self._heard_term()), link
        )

    def _psnap_req_frame(
        self, parts: List[int], link: _PeerLink
    ) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: self._wire(
            (A_PSNAP_REQ, mb, list(parts), self._heard_term()), link
        )

    # -- receive path ------------------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                conn, _peer = self._server.accept()
            except OSError:
                return  # server closed
            threading.Thread(
                target=self._read_conn, args=(conn,), daemon=True
            ).start()

    def _read_conn(self, conn: socket.socket) -> None:
        buf = bytearray()
        conn.settimeout(None)
        try:
            while True:
                data = conn.recv(1 << 16)
                if not data:
                    return
                buf.extend(data)
                self.metrics.count("net.bytes_recv", len(data))
                for term in unpack_coded_frames(buf):
                    if obs_spans.ACTIVE:
                        # Reader-thread span: frame decode + cache write
                        # (overlappable — the round never blocks on it).
                        with obs_spans.span(
                            "round.gossip_recv", wire=True,
                            fkind=str(term[0]) if term else "?",
                        ):
                            self._handle(term, conn)
                    else:
                        self._handle(term, conn)
        except (OSError, ValueError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _store_snap(self, m: str, blob: bytes) -> bool:
        """Anchor cache write; True when the blob was accepted. Ordered
        within one link, but reconnects can interleave: only a
        step-header >= the cached one replaces the anchor."""
        with self._lock:
            old = self._snaps.get(m)
            if (
                old is None
                or len(blob) < 8
                or struct.unpack("<Q", blob[:8])[0]
                >= struct.unpack("<Q", old[:8])[0]
            ):
                self._snaps[m] = blob
                return True
            return False

    def _store_delta(self, m: str, seq: int, keep: int, blob: bytes) -> bool:
        """Delta window write; True when `seq` is NEW and survived the
        prune (a stale redelivery must not trigger a re-relay). Prune
        against the window MAX: reconnect interleavings can deliver an
        old delta late — it must not re-enter past the keep bound."""
        with self._lock:
            window = self._deltas.setdefault(m, {})
            fresh = seq not in window
            window[seq] = blob
            hi = max(window)
            for s in [s for s in window if s <= hi - keep]:
                del window[s]
            return fresh and seq in window

    @staticmethod
    def _ccpt_seq(blob: bytes) -> Optional[int]:
        """Embedded seq of a CCPT partition blob (core.partition), or
        None for anything else — kept header-only so the transport stays
        payload-opaque."""
        if len(blob) >= 14 and bytes(blob[:4]) == b"CCPT":
            return struct.unpack_from("<Q", blob, 6)[0]
        return None

    def _store_dig(self, m: str, blob: bytes) -> bool:
        """Digest-vector cache write, newest-seq-wins (same reconnect
        interleaving hazard as `_store_snap`)."""
        with self._lock:
            old = self._digs.get(m)
            new_seq, old_seq = self._ccpt_seq(blob), (
                self._ccpt_seq(old) if old is not None else None
            )
            if (
                old is None
                or new_seq is None
                or old_seq is None
                or new_seq >= old_seq
            ):
                self._digs[m] = blob
                return True
            return False

    def _store_psnap(self, m: str, part: int, blob: bytes) -> bool:
        with self._lock:
            window = self._psnaps.setdefault(m, {})
            old = window.get(part)
            new_seq, old_seq = self._ccpt_seq(blob), (
                self._ccpt_seq(old) if old is not None else None
            )
            if (
                old is None
                or new_seq is None
                or old_seq is None
                or new_seq >= old_seq
            ):
                window[part] = blob
                return True
            return False

    def _serve_psnaps(self, requester: str, parts: List[int]) -> None:
        """Answer one `{psnap_req}`: push OUR stored psnap blobs for the
        requested partitions back to the requester (point-to-point on the
        direct link; a requester we hold no link to falls back to
        whole-snapshot resync on its side)."""
        link = self._links.get(requester)
        if link is None:
            return
        with self._lock:
            own = dict(self._psnaps.get(self.member, {}))
        for part in parts:
            blob = own.get(int(part))
            if blob is None:
                continue
            self.metrics.count("net.psnap_serves")
            link.enqueue(
                _PSNAP,
                self._psnap_frame(int(part), blob, link),
                meta={"origin": self.member, "part": int(part)},
            )

    def _handle(self, term, conn: Optional[socket.socket] = None) -> None:
        self.metrics.count("net.frames_recv")
        tag = term[0]
        if tag == A_METRICS_REQ:
            # In-band scrape: reply on the inbound connection and return
            # WITHOUT touching membership — the scraper is not a member.
            # A 2-element request carries the scraper's T1 (clock
            # piggyback); echo it with our T2 so the scraper can align.
            if conn is not None:
                t1 = term[1] if len(term) > 1 else None
                self._send_metrics_resp(conn, t1=t1)
            return
        if tag == A_QUERY:
            # Serve-plane read: same reply-on-inbound-connection contract
            # as the scrape — the querier never joins the membership. An
            # optional 3rd element is opaque router metadata (qid),
            # echoed back for correlation under failover/hedging.
            if conn is not None and len(term) > 1:
                qid = bytes(term[2]) if len(term) > 2 else None
                self._send_query_resp(conn, bytes(term[1]), qid=qid)
            return
        if tag == A_WRITE:
            # Ingest-plane write: same reply-on-inbound-connection
            # contract — the writer never joins the membership. The
            # handler BLOCKS this reader thread until the round loop
            # drains the write (bounded by the plane's ack timeout);
            # that is safe here because every inbound connection gets
            # its own reader thread.
            if conn is not None and len(term) > 1:
                wid = bytes(term[2]) if len(term) > 2 else None
                self._send_write_ack(conn, bytes(term[1]), wid=wid)
            return
        if tag == A_HELLO:
            # Link setup from a topo-aware peer: learn its zone, answer
            # with ours and the best codec we can decode of its offer.
            # Tolerant indexing: element 5 (T1) arrived with the clock
            # piggyback; older peers send 4 elements, and a hard unpack
            # here would close the whole read connection on mismatch.
            mb, zb, codecs = term[1], term[2], term[3]
            t1 = term[4] if len(term) > 4 else None
            m = mb.decode("utf-8")
            self.zones.learn(m, zb.decode("utf-8"))
            chosen = CODEC_ZLIB if CODEC_ZLIB in list(codecs) else CODEC_RAW
            if conn is not None:
                ack = [
                    A_HELLO_ACK,
                    self.member.encode("utf-8"),
                    self.zone.encode("utf-8"),
                    chosen,
                ]
                if t1 is not None:
                    ack.extend([float(t1), time.monotonic()])
                try:
                    conn.sendall(pack_frame(tuple(ack)))
                except OSError:
                    pass
            self.membership.observe(m)
            return
        if tag == A_SNAP:
            _, mb, blob, heard = term
            m = mb.decode("utf-8")
            obs_events.emit(
                "frame.recv", fkind=_SNAP, origin=m, bytes=len(blob)
            )
            if self._store_snap(m, blob) and self.zones.zone_of(m) == self.zone:
                # A zone-mate's own anchor: if we are this zone's relay
                # anchor, carry it across the DCN (no-op for leaves).
                self._relay_snap(m, blob, [(m, self.zone)])
        elif tag == A_RSNAP:
            _, ob, blob, path_t, heard = term
            origin = ob.decode("utf-8")
            path = [
                (pm.decode("utf-8"), pz.decode("utf-8")) for pm, pz in path_t
            ]
            for pm, pz in path:
                self.zones.learn(pm, pz)
            m = path[-1][0] if path else origin  # the actual wire sender
            obs_events.emit(
                "frame.recv",
                fkind=_SNAP,
                origin=origin,
                bytes=len(blob),
                hops=len(path),
            )
            if not ZoneRouter.loop_safe(path, self.member):
                self.metrics.count("topo.relay_loops")
                return
            if self._store_snap(origin, blob):
                self._relay_snap(origin, blob, path)
        elif tag == A_DELTA:
            _, mb, seq, keep, blob, heard = term
            m = mb.decode("utf-8")
            # Stage "recv" of the delta trace: the frame's own
            # {delta, Member, Seq, ...} term IS the trace context.
            obs_events.emit(
                "frame.recv",
                fkind=_DELTA,
                origin=m,
                dseq=int(seq),
                bytes=len(blob),
            )
            if (
                self._store_delta(m, int(seq), int(keep), blob)
                and self.zones.zone_of(m) == self.zone
            ):
                self._relay_delta(
                    m, int(seq), int(keep), blob, [(m, self.zone)]
                )
        elif tag == A_RDELTA:
            _, ob, seq, keep, blob, path_t, heard = term
            origin = ob.decode("utf-8")
            path = [
                (pm.decode("utf-8"), pz.decode("utf-8")) for pm, pz in path_t
            ]
            for pm, pz in path:
                self.zones.learn(pm, pz)
            m = path[-1][0] if path else origin
            obs_events.emit(
                "frame.recv",
                fkind=_DELTA,
                origin=origin,
                dseq=int(seq),
                bytes=len(blob),
                hops=len(path),
            )
            if not ZoneRouter.loop_safe(path, self.member):
                self.metrics.count("topo.relay_loops")
                return
            if self._store_delta(origin, int(seq), int(keep), blob):
                self._relay_delta(origin, int(seq), int(keep), blob, path)
        elif tag == A_DIG:
            _, mb, blob, heard = term
            m = mb.decode("utf-8")
            obs_events.emit(
                "frame.recv", fkind=_DIG, origin=m, bytes=len(blob)
            )
            if self._store_dig(m, blob) and self.zones.zone_of(m) == self.zone:
                self._relay_dig(m, blob, [(m, self.zone)])
        elif tag == A_RDIG:
            _, ob, blob, path_t, heard = term
            origin = ob.decode("utf-8")
            path = [
                (pm.decode("utf-8"), pz.decode("utf-8")) for pm, pz in path_t
            ]
            for pm, pz in path:
                self.zones.learn(pm, pz)
            m = path[-1][0] if path else origin
            obs_events.emit(
                "frame.recv", fkind=_DIG, origin=origin, bytes=len(blob),
                hops=len(path),
            )
            if not ZoneRouter.loop_safe(path, self.member):
                self.metrics.count("topo.relay_loops")
                return
            if self._store_dig(origin, blob):
                self._relay_dig(origin, blob, path)
        elif tag == A_PSNAP:
            _, mb, part, blob, heard = term
            m = mb.decode("utf-8")
            obs_events.emit(
                "frame.recv", fkind=_PSNAP, origin=m, part=int(part),
                bytes=len(blob),
            )
            self._store_psnap(m, int(part), blob)
        elif tag == A_PSNAP_REQ:
            _, mb, parts, heard = term
            m = mb.decode("utf-8")
            self.metrics.count("net.psnap_reqs_recv")
            self._serve_psnaps(m, [int(p) for p in parts])
        elif tag == A_PING:
            _, mb, heard = term
            m = mb.decode("utf-8")
        else:
            return  # unknown frame: ignore (forward compatibility)
        if m != self.member:
            self.membership.observe(m)
        self.membership.absorb(
            {k.decode("utf-8"): v for k, v in heard.items()}
        )

    # -- relay (anchors only; plan_relay returns [] for leaves) ------------

    def _relay_snap(self, origin: str, blob: bytes, path: _Path) -> None:
        def enq(link: _PeerLink, stamped: _Path, meta: Dict[str, object]):
            link.enqueue(
                _SNAP, self._rsnap_frame(origin, blob, stamped, link), meta
            )

        self._relay(_SNAP, origin, path, enq)

    def _relay_dig(self, origin: str, blob: bytes, path: _Path) -> None:
        def enq(link: _PeerLink, stamped: _Path, meta: Dict[str, object]):
            link.enqueue(
                _DIG, self._rdig_frame(origin, blob, stamped, link), meta
            )

        self._relay(_DIG, origin, path, enq)

    def _relay_delta(
        self, origin: str, seq: int, keep: int, blob: bytes, path: _Path
    ) -> None:
        def enq(link: _PeerLink, stamped: _Path, meta: Dict[str, object]):
            link.enqueue(
                _DELTA,
                self._rdelta_frame(origin, seq, keep, blob, stamped, link),
                meta,
            )

        self._relay(_DELTA, origin, path, enq, dseq=seq)

    def _relay(
        self,
        fkind: str,
        origin: str,
        path: _Path,
        enq: Callable[[_PeerLink, _Path, Dict[str, object]], None],
        dseq: Optional[int] = None,
    ) -> None:
        router = self.router
        if router is None:
            return
        targets = router.plan_relay(origin, path, sorted(self._links))
        if not targets:
            return
        stamped = path + [(self.member, self.zone)]
        trace: Dict[str, object] = {"origin": origin}
        if dseq is not None:
            trace["dseq"] = dseq
        for peer, cross in targets:
            link = self._links.get(peer)
            if link is None:
                continue
            enq(link, stamped, dict(trace, cross_zone=cross, relay=True))
        self.metrics.count("topo.relays")
        obs_events.emit(
            "frame.relay",
            fkind=fkind,
            hops=len(path),
            n_targets=len(targets),
            cross_zone=any(c for _, c in targets),
            **trace,
        )

    def _send_metrics_resp(self, conn: socket.socket, t1=None) -> None:
        """Answer one `{metrics_req}`: render a snapshot (never the live
        dicts) and write it back. Degrade-never-hang: the `tcp.send`
        fault point (drop or raised reset) and any real socket error
        close the connection, so the scraper sees EOF/error within its
        own timeout while the registry stays intact. When the request
        carried T1, the reply appends (T1, T2) for the clock piggyback."""
        from ..obs import export as obs_export

        self.metrics.count("net.scrapes")
        text = obs_export.prometheus_text(
            self.metrics, labels={"member": self.member}
        )
        resp = [
            A_METRICS_RESP, self.member.encode("utf-8"), text.encode("utf-8"),
        ]
        if t1 is not None:
            resp.extend([float(t1), time.monotonic()])
        frame = pack_frame(tuple(resp))
        try:
            if faults.ACTIVE and faults.fire("tcp.send") == "drop":
                self.metrics.count("net.fault_drops")
                raise OSError("injected scrape-reply drop")
            conn.sendall(frame)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass

    def _send_query_resp(
        self, conn: socket.socket, payload: bytes,
        qid: Optional[bytes] = None,
    ) -> None:
        """Answer one `{query, Payload}` via the installed serve plane.
        Degrade-never-hang, exactly like `_send_metrics_resp`: a handler
        failure (including an injected `serve.query` fault) or the
        `tcp.send` fault point closes the connection, so the querier
        sees EOF/error within its own timeout."""
        self.metrics.count("net.queries")
        try:
            handler = self.query_handler
            if handler is None:
                from ..serve import plane as serve_plane

                resp = serve_plane.encode(
                    {"member": self.member, "error": "no serve plane"}
                )
            else:
                resp = bytes(handler(payload))
        except Exception:  # noqa: BLE001 — degrade: close, querier times out
            try:
                conn.close()
            except OSError:
                pass
            return
        frame = pack_frame(
            (A_QUERY_RESP, self.member.encode("utf-8"), resp)
            if qid is None
            else (A_QUERY_RESP, self.member.encode("utf-8"), resp, qid)
        )
        try:
            if faults.ACTIVE and faults.fire("tcp.send") == "drop":
                self.metrics.count("net.fault_drops")
                raise OSError("injected query-reply drop")
            conn.sendall(frame)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass

    def _send_write_ack(
        self, conn: socket.socket, payload: bytes,
        wid: Optional[bytes] = None,
    ) -> None:
        """Answer one `{write, Payload}` via the installed ingest plane.
        Degrade-never-hang, exactly like `_send_query_resp`: a handler
        failure or the `tcp.send` fault point closes the connection, so
        the writer sees EOF/error within its own timeout and retries
        idempotently by write_id."""
        self.metrics.count("net.writes")
        try:
            handler = self.write_handler
            if handler is None:
                from ..serve import plane as serve_plane

                resp = serve_plane.encode(
                    {"member": self.member, "error": "no ingest plane"}
                )
            else:
                resp = bytes(handler(payload))
        except Exception:  # noqa: BLE001 — degrade: close, writer times out
            try:
                conn.close()
            except OSError:
                pass
            return
        frame = pack_frame(
            (A_WRITE_ACK, self.member.encode("utf-8"), resp)
            if wid is None
            else (A_WRITE_ACK, self.member.encode("utf-8"), resp, wid)
        )
        try:
            if faults.ACTIVE and faults.fire("tcp.send") == "drop":
                self.metrics.count("net.fault_drops")
                raise OSError("injected write-ack drop")
            conn.sendall(frame)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass

    # -- Transport: liveness ----------------------------------------------

    def _targets(self) -> List[Tuple[str, bool]]:
        """Where self's own frames go: every link when full-mesh, the
        router's (peer, cross_zone) picks once install_router() ran."""
        names = sorted(self._links)
        if self.router is None:
            return [(n, False) for n in names]
        return self.router.send_targets(names)

    def heartbeat(self) -> None:
        for peer, cross in self._targets():
            link = self._links.get(peer)
            if link is None:
                continue
            meta = {"cross_zone": True} if cross else None
            link.enqueue(_PING, self._ping_frame(link), meta=meta)

    def members(self) -> List[str]:
        return self.membership.members()

    def peers(self) -> List[str]:
        return [m for m in self.members() if m != self.member]

    def alive_members(self, timeout_s: float) -> List[str]:
        return self.membership.alive(timeout_s)

    # -- Transport: snapshots ---------------------------------------------

    def publish(self, blob: bytes) -> None:
        with self._lock:
            self._snaps[self.member] = blob
        path = [(self.member, self.zone)]
        for peer, cross in self._targets():
            link = self._links.get(peer)
            if link is None:
                continue
            if cross:
                # Self is its zone's anchor sending straight across the
                # DCN: stamp the path so the remote anchor can fan out.
                link.enqueue(
                    _SNAP,
                    self._rsnap_frame(self.member, blob, path, link),
                    meta={"origin": self.member, "cross_zone": True},
                )
            else:
                link.enqueue(
                    _SNAP,
                    self._snap_frame(blob, link),
                    meta={"origin": self.member},
                )

    def fetch(self, member: str) -> Optional[bytes]:
        with self._lock:
            return self._snaps.get(member)

    def fetch_head(self, member: str, n: int) -> Optional[bytes]:
        with self._lock:
            blob = self._snaps.get(member)
        return None if blob is None else blob[:n]

    def snapshot_members(self) -> List[str]:
        with self._lock:
            return sorted(self._snaps)

    # -- Transport: deltas -------------------------------------------------

    def publish_delta(self, seq: int, blob: bytes, keep: int = 16) -> None:
        # Compacted range frames (net.transport CCRF framing) ride the
        # wire as ordinary opaque delta blobs — peek the header only for
        # send-side observability (the meta `lo` shows up in queue-shed
        # diagnostics; one frame may carry many windows).
        lo = transport.frame_range(blob, seq)[0]
        if lo < seq and self.metrics is not None:
            self.metrics.count("net.tcp.coalesced_frames_sent")
        with self._lock:
            window = self._deltas.setdefault(self.member, {})
            window[seq] = blob
            for s in [s for s in window if s <= seq - keep]:
                del window[s]
        path = [(self.member, self.zone)]
        for peer, cross in self._targets():
            link = self._links.get(peer)
            if link is None:
                continue
            if cross:
                link.enqueue(
                    _DELTA,
                    self._rdelta_frame(self.member, seq, keep, blob, path, link),
                    meta={
                        "origin": self.member, "dseq": seq, "lo": lo,
                        "cross_zone": True,
                    },
                )
            else:
                link.enqueue(
                    _DELTA,
                    self._delta_frame(seq, keep, blob, link),
                    meta={"origin": self.member, "dseq": seq, "lo": lo},
                )

    def fetch_delta(self, member: str, seq: int) -> Optional[bytes]:
        with self._lock:
            return self._deltas.get(member, {}).get(seq)

    def delta_seqs(self, member: str) -> List[int]:
        with self._lock:
            return sorted(self._deltas.get(member, {}))

    def delta_members(self) -> List[str]:
        with self._lock:
            return sorted(self._deltas)

    # -- Transport: partition plane ----------------------------------------

    def publish_digest(self, blob: bytes) -> None:
        """Push the (tiny) digest-vector blob like a snapshot anchor;
        routed `{rdig}` across zones so remote fleets can detect
        divergence without ever pulling whole snapshots."""
        with self._lock:
            self._digs[self.member] = blob
        path = [(self.member, self.zone)]
        for peer, cross in self._targets():
            link = self._links.get(peer)
            if link is None:
                continue
            if cross:
                link.enqueue(
                    _DIG,
                    self._rdig_frame(self.member, blob, path, link),
                    meta={"origin": self.member, "cross_zone": True},
                )
            else:
                link.enqueue(
                    _DIG,
                    self._dig_frame(blob, link),
                    meta={"origin": self.member},
                )

    def fetch_digest(self, member: str) -> Optional[bytes]:
        with self._lock:
            return self._digs.get(member)

    def publish_psnap(self, part: int, blob: bytes) -> None:
        """Store-only: psnap bytes cross the wire exclusively on demand
        (`request_psnaps` -> `{psnap_req}` -> `{psnap}`) — broadcasting
        them would re-create the whole-snapshot bill the partition plane
        exists to avoid."""
        with self._lock:
            self._psnaps.setdefault(self.member, {})[int(part)] = blob

    def fetch_psnap(self, member: str, part: int) -> Optional[bytes]:
        with self._lock:
            return self._psnaps.get(member, {}).get(int(part))

    def request_psnaps(self, member: str, parts: List[int]) -> None:
        if not parts:
            return
        link = self._links.get(member)
        if link is None:
            return  # unreachable peer: caller falls back to full resync
        self.metrics.count("net.psnap_reqs_sent")
        link.enqueue(
            "psnap_req",  # no special queue policy: tiny and re-askable
            self._psnap_req_frame([int(p) for p in parts], link),
            meta={"origin": self.member},
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        for link in self._links.values():
            link.close()
