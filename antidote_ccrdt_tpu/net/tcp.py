"""Real TCP gossip peer: push-replicated blobs over `{packet,4}` frames.

Wire: the bridge's framing (`bridge.protocol.pack_frame`/`unpack_frames`
— u32_be length + ETF payload), so a BEAM host could join the gossip
mesh natively. Frame terms (member names as utf-8 binaries, `heard` the
sender's piggybacked `Membership.heard_ages` map):

    {snap,  Member, Blob, Heard}
    {delta, Member, Seq, Keep, Blob, Heard}
    {ping,  Member, Heard}
    {metrics_req}                      -> {metrics_resp, Member, Text}

`metrics_req` is the one request/reply pair: a scraper (Prometheus shim,
`scrape_metrics`, the dashboard) connects, sends the request, and gets
this member's OpenMetrics text back on the SAME inbound connection — the
only frame ever written back on an accepted socket. Scrapers are not
members: the request bypasses membership observation entirely.

Topology: full mesh over a static address book. Each member keeps ONE
outgoing connection per peer (`_PeerLink`) feeding from a bounded send
queue; inbound connections are accept-and-read only. Received blobs land
in local caches, so the `Transport` fetch surface is a local dict read —
anti-entropy stays pull-shaped above (`sweep_deltas` chains whatever has
arrived) while the medium is push-shaped below.

Failure behavior (the design goal: DEGRADE, never hang):

* connects/sends carry timeouts; a stalled peer costs the sender thread,
  never the caller;
* reconnects retry forever with exponential backoff + jitter (metrics:
  `net.retries`) — a dead peer is cheap to keep trying;
* the send queue is bounded with a drop-oldest-delta-keep-anchor policy:
  deltas are join-decomposed (`parallel.delta`), so a dropped delta only
  breaks the receiver's chain, and the periodically-published full
  anchor resyncs the gap (`sweep_deltas`'s fallback). Snapshots are
  latest-wins — a newly queued anchor replaces any queued older one;
* liveness comes from `net.membership` fed by every received frame, so
  a stalled peer decays ALIVE -> SUSPECT -> DEAD instead of blocking.

Frames are ENCODED AT SEND TIME (the queue holds builders, not bytes) so
piggybacked ages are measured when the frame actually leaves — a frame
that sat queued behind a dead link must not deliver stale "I heard X
recently" claims.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..bridge.protocol import pack_frame, unpack_frames
from ..core.etf import Atom
from ..obs import events as obs_events
from ..utils import faults
from ..utils.metrics import Metrics
from .membership import Membership

A_SNAP = Atom("snap")
A_DELTA = Atom("delta")
A_PING = Atom("ping")
A_METRICS_REQ = Atom("metrics_req")
A_METRICS_RESP = Atom("metrics_resp")

_SNAP, _DELTA, _PING = "snap", "delta", "ping"


def scrape_metrics(addr: Tuple[str, int], timeout: float = 2.0) -> Tuple[str, str]:
    """One-shot in-band scrape of a live `TcpTransport`: connect to its
    gossip listener, send `{metrics_req}`, return (member, OpenMetrics
    text). Bounded by `timeout` end-to-end — a wedged or fault-injected
    worker yields `socket.timeout`/`ConnectionError`, never a hang."""
    deadline = time.monotonic() + timeout
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(pack_frame((A_METRICS_REQ,)))
        buf = bytearray()
        while True:
            s.settimeout(max(0.01, deadline - time.monotonic()))
            data = s.recv(1 << 16)
            if not data:
                raise ConnectionError("scrape connection closed before reply")
            buf.extend(data)
            for term in unpack_frames(buf):
                if term[0] == A_METRICS_RESP:
                    return term[1].decode("utf-8"), term[2].decode("utf-8")


class _PeerLink:
    """One outgoing connection: bounded queue + sender thread with
    backoff. `enqueue` never blocks the caller; the queue policy keeps
    at most one snapshot (latest anchor) and one pending ping, and sheds
    the OLDEST delta first when full."""

    def __init__(
        self,
        name: str,
        addr: Tuple[str, int],
        rng: random.Random,
        metrics: Metrics,
        queue_max: int,
        connect_timeout: float,
        send_timeout: float,
        backoff_base: float,
        backoff_max: float,
    ):
        self.name = name  # peer's member name (frame.send events, gauges)
        self.addr = addr
        self.rng = rng
        self.metrics = metrics
        self.queue_max = queue_max
        self.connect_timeout = connect_timeout
        self.send_timeout = send_timeout
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        # (kind, build_frame: () -> bytes, meta: trace context carried to
        # the frame.send event — {origin, dseq} for deltas)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._sock: Optional[socket.socket] = None
        self._attempts = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _gauge_depth(self) -> None:
        # Called under self._cv: per-peer send-queue depth for the
        # dashboard (a climbing gauge = this peer's link is stalling).
        self.metrics.set(f"net.sendq.{self.name}", float(len(self._q)))

    def enqueue(
        self,
        kind: str,
        build_frame: Callable[[], bytes],
        meta: Optional[Dict[str, object]] = None,
    ) -> None:
        with self._cv:
            if self._stop:
                return
            if kind == _SNAP:
                # Latest-wins anchor: a queued older snapshot is dead weight.
                stale = [i for i, (k, _, _m) in enumerate(self._q) if k == _SNAP]
                for i in reversed(stale):
                    del self._q[i]
            elif kind == _PING and any(k == _PING for k, _, _m in self._q):
                return  # one pending ping is enough liveness signal
            if len(self._q) >= self.queue_max:
                # Backpressure: shed the oldest DELTA (anchors resync the
                # gap); only if no delta is queued shed the oldest frame.
                for i, (k, _, _m) in enumerate(self._q):
                    if k == _DELTA:
                        del self._q[i]
                        break
                else:
                    self._q.popleft()
                self.metrics.count("net.send_drops")
            self._q.append((kind, build_frame, meta or {}))
            self._gauge_depth()
            self._cv.notify()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)

    # -- sender thread -----------------------------------------------------

    def _backoff(self) -> float:
        d = min(self.backoff_max, self.backoff_base * (2.0 ** self._attempts))
        return d * (0.5 + self.rng.random())  # jitter in [0.5d, 1.5d)

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        try:
            s = socket.create_connection(self.addr, timeout=self.connect_timeout)
            s.settimeout(self.send_timeout)
            self._sock = s
            self._attempts = 0
            self.metrics.count("net.connects")
            return True
        except OSError:
            self._attempts += 1
            self.metrics.count("net.retries")
            return False

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                kind, build, meta = self._q[0]
            if not self._ensure_connected():
                with self._cv:
                    self._cv.wait(timeout=self._backoff())
                    if self._stop:
                        return
                continue
            frame = build()
            dropped = False
            try:
                # Fault point `tcp.send`: raise = connection reset mid-send
                # (exercises the reconnect/backoff path exactly like a real
                # ECONNRESET); drop = frame lost on the wire (the queue
                # treats it as sent — receivers resync via anchors).
                if faults.ACTIVE and faults.fire("tcp.send") == "drop":
                    dropped = True
                    self.metrics.count("net.fault_drops")
                else:
                    self._sock.sendall(frame)
            except OSError:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                self._attempts += 1
                self.metrics.count("net.retries")
                continue  # same frame retries after reconnect
            with self._cv:
                # Sent: drop it (the queue head may have been reshuffled
                # by the snap-replacement policy; remove by identity).
                try:
                    self._q.remove((kind, build, meta))
                except ValueError:
                    pass
                self._gauge_depth()
            if not dropped:
                self.metrics.count("net.frames_sent")
                self.metrics.count("net.bytes_sent", len(frame))
                # Emitted when the frame actually left (not at enqueue):
                # delta metas carry (origin, dseq) so the trace shows the
                # true wire time of each propagation hop.
                obs_events.emit(
                    "frame.send",
                    peer=self.name,
                    fkind=kind,
                    bytes=len(frame),
                    **meta,
                )


class TcpTransport:
    """`net.transport.Transport` over real sockets (see module docstring).

    `peers` is the static address book {member: (host, port)}; `bind`
    may use port 0 (the kernel-assigned address is `self.address`, for
    rendezvous schemes like the demo's address files). `members()`
    reports only members actually HEARD FROM (self included) — the
    address book is connectivity, membership is evidence — so start
    barriers wait for real traffic, exactly like heartbeat files."""

    def __init__(
        self,
        member: str,
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        peers: Optional[Dict[str, Tuple[str, int]]] = None,
        metrics: Optional[Metrics] = None,
        queue_max: int = 64,
        connect_timeout: float = 0.5,
        send_timeout: float = 2.0,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        seed: Optional[int] = None,
    ):
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        self.membership = Membership(member, metrics=self.metrics)
        self._rng = random.Random(
            seed if seed is not None else hash(member) & 0xFFFFFFFF
        )
        self._lock = threading.Lock()
        self._snaps: Dict[str, bytes] = {}
        self._deltas: Dict[str, Dict[int, bytes]] = {}
        self._closed = False

        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(bind)
        self._server.listen(16)
        self.address: Tuple[str, int] = self._server.getsockname()[:2]

        self._link_params = (
            queue_max, connect_timeout, send_timeout, backoff_base, backoff_max,
        )
        self._links: Dict[str, _PeerLink] = {}
        for name, addr in sorted((peers or {}).items()):
            self.add_peer(name, addr)

        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()

    def add_peer(self, name: str, addr: Tuple[str, int]) -> None:
        """Open (or keep) the outgoing link to `name`. Exists because
        port-0 binds can't know each other's addresses at construction —
        rendezvous (the demo's addr files) discovers them afterwards."""
        if name == self.member:
            return
        with self._lock:
            if name in self._links or self._closed:
                return
            self._links[name] = _PeerLink(
                name, tuple(addr), self._rng, self.metrics, *self._link_params
            )

    # -- frame builders (called at send time, see module docstring) --------

    def _heard_term(self) -> Dict[bytes, float]:
        return {
            m.encode("utf-8"): float(age)
            for m, age in self.membership.heard_ages().items()
        }

    def _snap_frame(self, blob: bytes) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: pack_frame((A_SNAP, mb, blob, self._heard_term()))

    def _delta_frame(self, seq: int, keep: int, blob: bytes) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: pack_frame((A_DELTA, mb, seq, keep, blob, self._heard_term()))

    def _ping_frame(self) -> Callable[[], bytes]:
        mb = self.member.encode("utf-8")
        return lambda: pack_frame((A_PING, mb, self._heard_term()))

    # -- receive path ------------------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                conn, _peer = self._server.accept()
            except OSError:
                return  # server closed
            threading.Thread(
                target=self._read_conn, args=(conn,), daemon=True
            ).start()

    def _read_conn(self, conn: socket.socket) -> None:
        buf = bytearray()
        conn.settimeout(None)
        try:
            while True:
                data = conn.recv(1 << 16)
                if not data:
                    return
                buf.extend(data)
                self.metrics.count("net.bytes_recv", len(data))
                for term in unpack_frames(buf):
                    self._handle(term, conn)
        except (OSError, ValueError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, term, conn: Optional[socket.socket] = None) -> None:
        self.metrics.count("net.frames_recv")
        tag = term[0]
        if tag == A_METRICS_REQ:
            # In-band scrape: reply on the inbound connection (the only
            # write-back frame) and return WITHOUT touching membership —
            # the scraper is not a mesh member.
            if conn is not None:
                self._send_metrics_resp(conn)
            return
        if tag == A_SNAP:
            _, mb, blob, heard = term
            m = mb.decode("utf-8")
            obs_events.emit(
                "frame.recv", fkind=_SNAP, origin=m, bytes=len(blob)
            )
            with self._lock:
                # Ordered within one link, but reconnects can interleave:
                # only a step-header >= the cached one replaces the anchor.
                old = self._snaps.get(m)
                if (
                    old is None
                    or len(blob) < 8
                    or struct.unpack("<Q", blob[:8])[0]
                    >= struct.unpack("<Q", old[:8])[0]
                ):
                    self._snaps[m] = blob
        elif tag == A_DELTA:
            _, mb, seq, keep, blob, heard = term
            m = mb.decode("utf-8")
            # Stage "recv" of the delta trace: the frame's own
            # {delta, Member, Seq, ...} term IS the trace context.
            obs_events.emit(
                "frame.recv",
                fkind=_DELTA,
                origin=m,
                dseq=int(seq),
                bytes=len(blob),
            )
            with self._lock:
                window = self._deltas.setdefault(m, {})
                window[int(seq)] = blob
                # Prune against the window MAX: reconnect interleavings can
                # deliver an old delta late — it must not re-enter past the
                # keep bound.
                hi = max(window)
                for s in [s for s in window if s <= hi - keep]:
                    del window[s]
        elif tag == A_PING:
            _, mb, heard = term
            m = mb.decode("utf-8")
        else:
            return  # unknown frame: ignore (forward compatibility)
        self.membership.observe(m)
        self.membership.absorb(
            {k.decode("utf-8"): v for k, v in heard.items()}
        )

    def _send_metrics_resp(self, conn: socket.socket) -> None:
        """Answer one `{metrics_req}`: render a snapshot (never the live
        dicts) and write it back. Degrade-never-hang: the `tcp.send`
        fault point (drop or raised reset) and any real socket error
        close the connection, so the scraper sees EOF/error within its
        own timeout while the registry stays intact."""
        from ..obs import export as obs_export

        self.metrics.count("net.scrapes")
        text = obs_export.prometheus_text(
            self.metrics, labels={"member": self.member}
        )
        frame = pack_frame(
            (A_METRICS_RESP, self.member.encode("utf-8"), text.encode("utf-8"))
        )
        try:
            if faults.ACTIVE and faults.fire("tcp.send") == "drop":
                self.metrics.count("net.fault_drops")
                raise OSError("injected scrape-reply drop")
            conn.sendall(frame)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass

    # -- Transport: liveness ----------------------------------------------

    def heartbeat(self) -> None:
        for link in self._links.values():
            link.enqueue(_PING, self._ping_frame())

    def members(self) -> List[str]:
        return self.membership.members()

    def peers(self) -> List[str]:
        return [m for m in self.members() if m != self.member]

    def alive_members(self, timeout_s: float) -> List[str]:
        return self.membership.alive(timeout_s)

    # -- Transport: snapshots ---------------------------------------------

    def publish(self, blob: bytes) -> None:
        with self._lock:
            self._snaps[self.member] = blob
        for link in self._links.values():
            link.enqueue(
                _SNAP, self._snap_frame(blob), meta={"origin": self.member}
            )

    def fetch(self, member: str) -> Optional[bytes]:
        with self._lock:
            return self._snaps.get(member)

    def fetch_head(self, member: str, n: int) -> Optional[bytes]:
        with self._lock:
            blob = self._snaps.get(member)
        return None if blob is None else blob[:n]

    def snapshot_members(self) -> List[str]:
        with self._lock:
            return sorted(self._snaps)

    # -- Transport: deltas -------------------------------------------------

    def publish_delta(self, seq: int, blob: bytes, keep: int = 16) -> None:
        with self._lock:
            window = self._deltas.setdefault(self.member, {})
            window[seq] = blob
            for s in [s for s in window if s <= seq - keep]:
                del window[s]
        for link in self._links.values():
            link.enqueue(
                _DELTA,
                self._delta_frame(seq, keep, blob),
                meta={"origin": self.member, "dseq": seq},
            )

    def fetch_delta(self, member: str, seq: int) -> Optional[bytes]:
        with self._lock:
            return self._deltas.get(member, {}).get(seq)

    def delta_seqs(self, member: str) -> List[int]:
        with self._lock:
            return sorted(self._deltas.get(member, {}))

    def delta_members(self) -> List[str]:
        with self._lock:
            return sorted(self._deltas)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        for link in self._links.values():
            link.close()
