"""Deterministic in-process simulated transport for chaos testing.

Replay-based convergence checking (the approach "Automatically Verifying
Replication-aware Linearizability" argues CRDT stacks need) requires the
fault schedule to be EXACTLY reproducible: same seed -> same drops, same
duplicates, same delivery order, bit-identical final states. So the
simulator owns ALL nondeterminism sources:

* a VIRTUAL clock (`SimNet.time`) advanced only by `advance`/`run_until`
  — no wall clock anywhere; `Membership` runs on it via its injected
  `now`;
* one seeded `random.Random` consumed in a deterministic order (the
  driver steps members single-threaded; there are no threads in here);
* a message heap ordered by (delivery time, send counter) so latency
  ties break deterministically.

Faults: per-message latency sampled from a range (which yields
reordering for free), iid loss and duplication probabilities, named
partitions (`partition`/`heal` — messages dropped at send time when
src and dst are in different groups), and member crashes (`crash` — a
crashed member neither sends nor receives, and its transport raises on
further use by the driver).

Messages carry the same logical payloads as `net.tcp` frames — the
blobs are the REAL serialized bytes (`GossipNode` encodes above the
transport), so chaos runs exercise the production encode/decode and
validation paths, not a shortcut."""

from __future__ import annotations

import heapq
import random
from typing import Dict, List, Optional, Tuple

from ..obs import events as obs_events
from ..obs import spans as obs_spans
from ..topo import ZoneMap, ZoneRouter, zone_from_env
from ..utils.metrics import Metrics
from . import transport
from .membership import Membership


class SimNet:
    """The shared medium: clock, fault injection, message scheduling."""

    def __init__(
        self,
        seed: int = 0,
        latency: Tuple[float, float] = (0.001, 0.02),
        loss: float = 0.0,
        dup: float = 0.0,
        metrics: Optional[Metrics] = None,
        link_latency: Optional[
            Dict[Tuple[str, str], Tuple[float, float]]
        ] = None,
    ):
        self.rng = random.Random(seed)
        self.latency = latency
        # Per-DIRECTION latency override {(src, dst): (lo, hi)}: lets a
        # drill make A->B slow and B->A fast (asymmetric RTT — exactly
        # the error term of the NTP-style offset estimate in obs/spans).
        self.link_latency = dict(link_latency or {})
        self.loss = loss
        self.dup = dup
        self.metrics = metrics if metrics is not None else Metrics()
        self.time = 0.0
        self._heap: List[Tuple[float, int, str, tuple]] = []
        self._counter = 0
        self._members: Dict[str, "SimTransport"] = {}
        self._groups: Optional[List[set]] = None
        self._crashed: set = set()

    # -- topology ----------------------------------------------------------

    def join(self, member: str, zone: Optional[str] = None) -> "SimTransport":
        """Add a member; `zone` opts it into the topo/ layout. The shared
        medium is the zone oracle: every existing member learns the
        newcomer's zone and vice versa (config-file discovery collapses
        to a dict in-process), so drills exercise routing, not gossip
        of the zone map itself — `net.tcp` covers that via hellos."""
        t = SimTransport(self, member, zone=zone)
        for other in self._members.values():
            other.zones.learn(member, t.zone)
            t.zones.learn(other.member, other.zone)
        self._members[member] = t
        return t

    def partition(self, *groups) -> None:
        """Split the network: members in different groups cannot exchange
        messages (members in no listed group are isolated)."""
        self._groups = [set(g) for g in groups]
        obs_events.emit(
            "sim.partition",
            groups=[sorted(g) for g in self._groups],
            vt=self.time,
        )

    def heal(self) -> None:
        self._groups = None
        obs_events.emit("sim.heal", vt=self.time)

    def crash(self, member: str) -> None:
        """Permanently silence `member`: no sends, no deliveries. Its
        queued in-flight messages are dropped at delivery time."""
        self._crashed.add(member)
        obs_events.emit("sim.crash", peer=member, vt=self.time)

    def reachable(self, src: str, dst: str) -> bool:
        if src in self._crashed or dst in self._crashed:
            return False
        if self._groups is None:
            return True
        return any(src in g and dst in g for g in self._groups)

    # -- transmission ------------------------------------------------------

    def send(self, src: str, dst: str, msg: tuple) -> None:
        """Apply the fault model and schedule delivery. Partition/crash
        filtering happens at SEND time (a message in flight when a
        partition forms still arrives — links don't retroactively eat
        packets); crash filtering repeats at delivery."""
        if not self.reachable(src, dst):
            self.metrics.count("net.sim_unreachable")
            obs_events.emit(
                "sim.drop", cause="unreachable", src=src, dst=dst,
                fkind=str(msg[0]), vt=self.time,
            )
            return
        copies = 1
        if self.rng.random() < self.loss:
            self.metrics.count("net.sim_lost")
            obs_events.emit(
                "sim.drop", cause="loss", src=src, dst=dst,
                fkind=str(msg[0]), vt=self.time,
            )
            copies = 0
        elif self.rng.random() < self.dup:
            self.metrics.count("net.sim_duplicated")
            copies = 2
        lo, hi = self.link_latency.get((src, dst), self.latency)
        for _ in range(copies):
            at = self.time + lo + (hi - lo) * self.rng.random()
            self._counter += 1
            heapq.heappush(self._heap, (at, self._counter, dst, msg))
            self.metrics.count("net.sim_msgs")

    def advance(self, dt: float) -> None:
        self.run_until(self.time + dt)

    def run_until(self, t: float) -> None:
        """Advance the virtual clock to `t`, delivering everything due."""
        while self._heap and self._heap[0][0] <= t:
            at, _n, dst, msg = heapq.heappop(self._heap)
            self.time = max(self.time, at)
            if dst in self._crashed:
                continue
            self._members[dst]._deliver(msg)
        self.time = max(self.time, t)


class SimTransport:
    """`net.transport.Transport` over a `SimNet` (see module docstring).

    Cache shape mirrors `net.tcp.TcpTransport`: pushes land in local
    snapshot/delta dicts, fetches read them; liveness is a `Membership`
    on the virtual clock, fed by piggybacked ages on every message."""

    def __init__(self, net: SimNet, member: str, zone: Optional[str] = None):
        self.net = net
        self.member = member
        self.metrics = net.metrics
        self.zone = zone if zone is not None else zone_from_env()
        self.zones = ZoneMap(member, self.zone)
        self.router: Optional[ZoneRouter] = None
        self.membership = Membership(
            member, now=lambda: net.time, metrics=net.metrics
        )
        self._snaps: Dict[str, bytes] = {}
        self._deltas: Dict[str, Dict[int, bytes]] = {}
        # Partition plane caches, mirroring net.tcp: digest vectors are
        # pushed; psnaps are stored locally and only transferred when a
        # peer requests divergent partitions.
        self._digs: Dict[str, bytes] = {}
        self._psnaps: Dict[str, Dict[int, bytes]] = {}
        # Clock model for offset-estimation drills: each member reads
        # the shared virtual clock through its own constant skew, and
        # `clock_exchange` runs the same T1/T2/T3 protocol the tcp hello
        # piggybacks — deterministically, so tests can bound the offset
        # error by the configured RTT asymmetry.
        self.clock_skew = 0.0
        self.clock = obs_spans.ClockSync()
        # Serve plane: in-band "query" messages are answered by this
        # handler when installed; replies land in the querier's
        # `query_resps` list at delivery time (the sim's synchronous
        # analog of tcp's reply-on-inbound-connection).
        self.query_handler = None
        self.query_resps: List[Tuple[str, bytes]] = []
        # Router metadata: qid-keyed responses + cancellation. A reply
        # whose qid was cancelled before delivery is DROPPED (counted,
        # `net.query_cancelled_drops`) — the sim analog of the router
        # reaping a hedge loser / failed-over attempt, and what the
        # zero-duplicate-answer drill asserts on.
        self.query_results: Dict[bytes, Tuple[str, bytes]] = {}
        # Insertion-ordered so the cap in `cancel_query` evicts the
        # oldest cancellations first — it only needs to cover qids whose
        # (possibly dup-delivered) responses can still be in flight, so
        # long chaos drills don't accumulate spent qids forever.
        self._query_cancelled: Dict[bytes, None] = {}
        # Ingest plane: in-band "write" messages, the write tier's twin
        # of the query plumbing above — same synchronous handler, same
        # wid-keyed results + cancellation-drop discipline, so sim
        # drills shake owner failover / duplicate delivery exactly as
        # the sockets would.
        self.write_handler = None
        self.write_acks: List[Tuple[str, bytes]] = []
        self.write_results: Dict[bytes, Tuple[str, bytes]] = {}
        self._write_cancelled: Dict[bytes, None] = {}

    def local_clock(self) -> float:
        """This member's view of time: virtual clock + its skew."""
        return self.net.time + self.clock_skew

    def clock_exchange(self, peer: str) -> None:
        """Start one NTP-style exchange with `peer`; the estimate lands
        in `self.clock` when the reply is delivered."""
        self._check_live()
        self._send(
            peer, ("clock_req", self.member, self.local_clock()), False, 0
        )

    def install_serve(self, plane) -> None:
        """Attach a serve plane (or any bytes->bytes handler), exactly
        as `TcpTransport.install_serve` — sim drills exercise the same
        query path chaos-deterministically. Payloads are opaque here
        too: an rtrace ``"trace"`` context and the response ``"rtrace"``
        echo round-trip byte-identically with the tcp surface."""
        handler_for = getattr(plane, "handler_for", None)
        if callable(handler_for):
            self.query_handler = handler_for("sim")
        else:
            self.query_handler = getattr(plane, "handle", plane)

    def query(self, peer: str, payload: bytes,
              qid: Optional[bytes] = None) -> None:
        """Send one serve-plane read to `peer`; the response arrives in
        `self.query_resps` as (peer, bytes) once the net delivers it.
        With `qid` (opaque router metadata, echoed by the peer) it ALSO
        lands in `self.query_results[qid]` — unless `cancel_query(qid)`
        ran first, in which case the late answer is dropped."""
        self._check_live()
        msg = (
            ("query", self.member, bytes(payload)) if qid is None
            else ("query", self.member, bytes(payload), bytes(qid))
        )
        self._send(peer, msg, False, len(payload))

    def install_ingest(self, plane) -> None:
        """Attach an ingest plane (or any bytes->bytes handler), exactly
        as `TcpTransport.install_ingest` — sim drills exercise the same
        write path chaos-deterministically."""
        handler_for = getattr(plane, "handler_for", None)
        if callable(handler_for):
            self.write_handler = handler_for("sim")
        else:
            self.write_handler = getattr(plane, "handle", plane)

    def write(self, peer: str, payload: bytes,
              wid: Optional[bytes] = None) -> None:
        """Send one ingest-plane write to `peer`; the ack arrives in
        `self.write_acks` as (peer, bytes) once the net delivers it.
        With `wid` (opaque router metadata, echoed by the peer) it ALSO
        lands in `self.write_results[wid]` — unless `cancel_write(wid)`
        ran first, in which case the late ack is dropped (the payload's
        write_id still dedups any retry at the plane)."""
        self._check_live()
        msg = (
            ("write", self.member, bytes(payload)) if wid is None
            else ("write", self.member, bytes(payload), bytes(wid))
        )
        self._send(peer, msg, False, len(payload))

    def cancel_write(self, wid: bytes) -> None:
        """Abandon an in-flight wid: its ack, if it ever arrives, is
        dropped instead of delivered — same bounded-set discipline as
        `cancel_query`. Note this abandons only the ACK; whether the
        write folded is the plane's business, which is why retries
        carry the same write_id."""
        wid = bytes(wid)
        self._write_cancelled[wid] = None
        while len(self._write_cancelled) > 1024:
            self._write_cancelled.pop(next(iter(self._write_cancelled)))
        self.write_results.pop(wid, None)

    def cancel_query(self, qid: bytes) -> None:
        """Abandon an in-flight qid: its response, if it ever arrives,
        is dropped instead of delivered — the sim's router-cancellation
        analog (a hedge loser must not surface a duplicate answer). The
        set only needs to cover in-flight qids, so it is bounded: beyond
        the cap the oldest cancellations (whose replies are long since
        dropped or never coming) are forgotten."""
        qid = bytes(qid)
        self._query_cancelled[qid] = None
        while len(self._query_cancelled) > 1024:
            self._query_cancelled.pop(next(iter(self._query_cancelled)))
        self.query_results.pop(qid, None)

    def install_router(self, timeout_s: float = 2.0) -> ZoneRouter:
        """Switch from full-mesh to the zone-aware topology, exactly as
        `TcpTransport.install_router` — so chaos drills shake the SAME
        routing policy the real sockets run."""
        self.router = ZoneRouter(
            self.member,
            self.zone,
            self.zones,
            membership=self.membership,
            timeout_s=timeout_s,
            metrics=self.metrics,
        )
        return self.router

    # -- send side ---------------------------------------------------------

    def _check_live(self) -> None:
        if self.member in self.net._crashed:
            raise RuntimeError(f"{self.member} is crashed (driver bug)")

    def _targets(self) -> List[Tuple[str, bool]]:
        peers = [m for m in sorted(self.net._members) if m != self.member]
        if self.router is None:
            return [(m, False) for m in peers]
        return self.router.send_targets(peers)

    def _send(self, dst: str, msg_base: tuple, cross: bool, nbytes: int) -> None:
        if cross:
            self.metrics.count("topo.cross_zone.frames")
            self.metrics.count("topo.cross_zone.bytes", nbytes)
        # heard_ages is per-send so every copy carries fresh evidence
        # (matches tcp's encode-at-send-time rule).
        self.net.send(
            self.member, dst,
            msg_base + (dict(self.membership.heard_ages()),),
        )

    def heartbeat(self) -> None:
        self._check_live()
        for dst, cross in self._targets():
            self._send(dst, ("ping", self.member), cross, 0)

    def publish(self, blob: bytes) -> None:
        self._check_live()
        self._snaps[self.member] = blob
        path = [(self.member, self.zone)]
        for dst, cross in self._targets():
            if cross:
                self._send(
                    dst, ("rsnap", self.member, blob, path), True, len(blob)
                )
            else:
                self._send(dst, ("snap", self.member, blob), False, 0)

    def publish_delta(self, seq: int, blob: bytes, keep: int = 16) -> None:
        self._check_live()
        window = self._deltas.setdefault(self.member, {})
        window[seq] = blob
        for s in [s for s in window if s <= seq - keep]:
            del window[s]
        path = [(self.member, self.zone)]
        for dst, cross in self._targets():
            if cross:
                self._send(
                    dst,
                    ("rdelta", self.member, seq, keep, blob, path),
                    True,
                    len(blob),
                )
            else:
                self._send(
                    dst, ("delta", self.member, seq, keep, blob), False, 0
                )

    # -- partition plane ---------------------------------------------------

    @staticmethod
    def _ccpt_seq(blob: bytes) -> Optional[int]:
        import struct as _struct

        if len(blob) >= 14 and bytes(blob[:4]) == b"CCPT":
            return _struct.unpack_from("<Q", blob, 6)[0]
        return None

    def publish_digest(self, blob: bytes) -> None:
        self._check_live()
        self._digs[self.member] = blob
        path = [(self.member, self.zone)]
        for dst, cross in self._targets():
            if cross:
                self._send(
                    dst, ("rdig", self.member, blob, path), True, len(blob)
                )
            else:
                self._send(dst, ("dig", self.member, blob), False, 0)

    def fetch_digest(self, member: str) -> Optional[bytes]:
        return self._digs.get(member)

    def publish_psnap(self, part: int, blob: bytes) -> None:
        self._check_live()
        self._psnaps.setdefault(self.member, {})[int(part)] = blob

    def fetch_psnap(self, member: str, part: int) -> Optional[bytes]:
        return self._psnaps.get(member, {}).get(int(part))

    def request_psnaps(self, member: str, parts: List[int]) -> None:
        self._check_live()
        if parts:
            self.metrics.count("net.psnap_reqs_sent")
            self._send(
                member, ("psnap_req", self.member, list(parts)), False, 0
            )

    # -- receive side ------------------------------------------------------

    def _store_snap(self, src: str, blob: bytes) -> bool:
        old = self._snaps.get(src)
        # Same reorder guard as tcp: only a >= step header replaces.
        import struct as _struct

        if (
            old is None
            or len(blob) < 8
            or _struct.unpack("<Q", blob[:8])[0]
            >= _struct.unpack("<Q", old[:8])[0]
        ):
            self._snaps[src] = blob
            return True
        return False

    def _store_delta(self, src: str, seq: int, keep: int, blob: bytes) -> bool:
        window = self._deltas.setdefault(src, {})
        fresh = seq not in window
        window[seq] = blob
        if fresh and blob[:4] == transport.FRAME_MAGIC:
            # Compacted range frame (CCRF) landed — receive-side mirror
            # of the publisher's ingest.coalesced_frames counter, so sim
            # chaos drills can assert compaction actually crossed the
            # (lossy) wire and not just left the publisher.
            self.metrics.count("net.sim.coalesced_frames_recv")
        # Prune against the window MAX, not this message's seq: a
        # reordered old delta must not re-enter past the keep bound.
        hi = max(window)
        for s in [s for s in window if s <= hi - keep]:
            del window[s]
        return fresh and seq in window

    def _store_dig(self, src: str, blob: bytes) -> bool:
        old = self._digs.get(src)
        new_seq = self._ccpt_seq(blob)
        old_seq = self._ccpt_seq(old) if old is not None else None
        if (
            old is None
            or new_seq is None
            or old_seq is None
            or new_seq >= old_seq
        ):
            self._digs[src] = blob
            return True
        return False

    def _store_psnap(self, src: str, part: int, blob: bytes) -> bool:
        window = self._psnaps.setdefault(src, {})
        old = window.get(part)
        new_seq = self._ccpt_seq(blob)
        old_seq = self._ccpt_seq(old) if old is not None else None
        if (
            old is None
            or new_seq is None
            or old_seq is None
            or new_seq >= old_seq
        ):
            window[part] = blob
            return True
        return False

    def _deliver(self, msg: tuple) -> None:
        if obs_spans.ACTIVE:
            # Same phase name as the tcp reader thread: frame ingest.
            with obs_spans.span(
                "round.gossip_recv", wire=True, fkind=str(msg[0]),
                sim_member=self.member,
            ):
                self._deliver_inner(msg)
        else:
            self._deliver_inner(msg)

    def _deliver_inner(self, msg: tuple) -> None:
        kind, src = msg[0], msg[1]
        heard = msg[-1]
        sender = src
        if kind == "clock_req":
            # Reply with (echoed T1, our clock at receipt): the
            # requester completes the offset estimate at delivery.
            t1 = msg[2]
            self._send(
                src,
                ("clock_resp", self.member, t1, self.local_clock()),
                False,
                0,
            )
        elif kind == "clock_resp":
            t1, t2 = msg[2], msg[3]
            self.clock.note(src, t1, t2, self.local_clock())
        elif kind == "snap":
            blob = msg[2]
            if self._store_snap(src, blob) and (
                self.zones.zone_of(src) == self.zone
            ):
                self._relay("snap", src, [(src, self.zone)],
                            lambda path: ("rsnap", src, blob, path), len(blob))
        elif kind == "rsnap":
            _k, origin, blob, path = msg[:4]
            for pm, pz in path:
                self.zones.learn(pm, pz)
            sender = path[-1][0] if path else origin
            if not ZoneRouter.loop_safe(path, self.member):
                self.metrics.count("topo.relay_loops")
                return
            if self._store_snap(origin, blob):
                self._relay("snap", origin, path,
                            lambda p: ("rsnap", origin, blob, p), len(blob))
        elif kind == "delta":
            _k, _s, seq, keep, blob = msg[:5]
            if self._store_delta(src, seq, keep, blob) and (
                self.zones.zone_of(src) == self.zone
            ):
                self._relay(
                    "delta", src, [(src, self.zone)],
                    lambda p: ("rdelta", src, seq, keep, blob, p),
                    len(blob), dseq=seq,
                )
        elif kind == "rdelta":
            _k, origin, seq, keep, blob, path = msg[:6]
            for pm, pz in path:
                self.zones.learn(pm, pz)
            sender = path[-1][0] if path else origin
            if not ZoneRouter.loop_safe(path, self.member):
                self.metrics.count("topo.relay_loops")
                return
            if self._store_delta(origin, seq, keep, blob):
                self._relay(
                    "delta", origin, path,
                    lambda p: ("rdelta", origin, seq, keep, blob, p),
                    len(blob), dseq=seq,
                )
        elif kind == "dig":
            blob = msg[2]
            if self._store_dig(src, blob) and (
                self.zones.zone_of(src) == self.zone
            ):
                self._relay("dig", src, [(src, self.zone)],
                            lambda p: ("rdig", src, blob, p), len(blob))
        elif kind == "rdig":
            _k, origin, blob, path = msg[:4]
            for pm, pz in path:
                self.zones.learn(pm, pz)
            sender = path[-1][0] if path else origin
            if not ZoneRouter.loop_safe(path, self.member):
                self.metrics.count("topo.relay_loops")
                return
            if self._store_dig(origin, blob):
                self._relay("dig", origin, path,
                            lambda p: ("rdig", origin, blob, p), len(blob))
        elif kind == "psnap":
            _k, _s, part, blob = msg[:4]
            self._store_psnap(src, int(part), blob)
        elif kind == "query":
            payload = msg[2]
            # Every frame carries the piggybacked heard-ages dict as its
            # last element, so a qid-bearing query is a 5-tuple and a
            # legacy qid-less one a 4-tuple.
            qid = bytes(msg[3]) if len(msg) > 4 else None
            handler = self.query_handler
            self.metrics.count("net.queries")
            if handler is not None:
                try:
                    resp = bytes(handler(bytes(payload)))
                except Exception as e:  # noqa: BLE001 — degrade, never wedge
                    import json as _json

                    resp = _json.dumps({"error": str(e)}).encode("utf-8")
            else:
                import json as _json

                resp = _json.dumps({"error": "no serve plane"}).encode("utf-8")
            out = (
                ("query_resp", self.member, resp) if qid is None
                else ("query_resp", self.member, resp, qid)
            )
            self._send(src, out, False, len(resp))
        elif kind == "query_resp":
            qid = bytes(msg[3]) if len(msg) > 4 else None
            if qid is not None and qid in self._query_cancelled:
                # Cancelled in flight: the router already moved on; a
                # late duplicate answer must not surface. Keep the qid
                # in the (bounded) cancel set: a dup-delivered copy of
                # this response may still be in flight behind it.
                self.metrics.count("net.query_cancelled_drops")
            else:
                self.query_resps.append((src, bytes(msg[2])))
                if qid is not None:
                    self.query_results[qid] = (src, bytes(msg[2]))
        elif kind == "write":
            payload = msg[2]
            # Same tuple convention as "query": the piggybacked heard
            # dict is the last element, so wid-bearing writes are
            # 5-tuples and wid-less ones 4-tuples.
            wid = bytes(msg[3]) if len(msg) > 4 else None
            handler = self.write_handler
            self.metrics.count("net.writes")
            if handler is not None:
                try:
                    resp = bytes(handler(bytes(payload)))
                except Exception as e:  # noqa: BLE001 — degrade, never wedge
                    import json as _json

                    resp = _json.dumps({"error": str(e)}).encode("utf-8")
            else:
                import json as _json

                resp = _json.dumps(
                    {"error": "no ingest plane"}
                ).encode("utf-8")
            out = (
                ("write_ack", self.member, resp) if wid is None
                else ("write_ack", self.member, resp, wid)
            )
            self._send(src, out, False, len(resp))
        elif kind == "write_ack":
            wid = bytes(msg[3]) if len(msg) > 4 else None
            if wid is not None and wid in self._write_cancelled:
                # Cancelled in flight: the router already failed over; a
                # late duplicate ack must not surface (the successor's
                # ack is the one the client keeps).
                self.metrics.count("net.write_cancelled_drops")
            else:
                self.write_acks.append((src, bytes(msg[2])))
                if wid is not None:
                    self.write_results[wid] = (src, bytes(msg[2]))
        elif kind == "psnap_req":
            parts = msg[2]
            self.metrics.count("net.psnap_reqs_recv")
            own = self._psnaps.get(self.member, {})
            for p in parts:
                blob = own.get(int(p))
                if blob is None:
                    continue
                self.metrics.count("net.psnap_serves")
                self._send(
                    src, ("psnap", self.member, int(p), blob), False, len(blob)
                )
        if sender != self.member:
            self.membership.observe(sender)
        self.membership.absorb(heard)

    def _relay(
        self,
        fkind: str,
        origin: str,
        path: list,
        mk_msg,
        nbytes: int,
        dseq: Optional[int] = None,
    ) -> None:
        """Forward an accepted frame per `plan_relay` (no-op for leaves
        and full-mesh transports). Mirrors tcp's relay: stamps self onto
        the path, counts cross-zone traffic, emits `frame.relay`."""
        router = self.router
        if router is None:
            return
        candidates = [m for m in sorted(self.net._members) if m != self.member]
        targets = router.plan_relay(origin, path, candidates)
        if not targets:
            return
        stamped = list(path) + [(self.member, self.zone)]
        for dst, cross in targets:
            self._send(dst, mk_msg(stamped), cross, nbytes)
        self.metrics.count("topo.relays")
        ev: Dict[str, object] = {
            "member": self.member,
            "fkind": fkind,
            "origin": origin,
            "hops": len(path),
            "n_targets": len(targets),
            "cross_zone": any(c for _, c in targets),
            "vt": self.net.time,
        }
        if dseq is not None:
            ev["dseq"] = dseq
        obs_events.emit("frame.relay", **ev)

    # -- Transport reads ---------------------------------------------------

    def members(self) -> List[str]:
        return self.membership.members()

    def peers(self) -> List[str]:
        return [m for m in self.members() if m != self.member]

    def alive_members(self, timeout_s: float) -> List[str]:
        return self.membership.alive(timeout_s)

    def fetch(self, member: str) -> Optional[bytes]:
        return self._snaps.get(member)

    def fetch_head(self, member: str, n: int) -> Optional[bytes]:
        blob = self._snaps.get(member)
        return None if blob is None else blob[:n]

    def snapshot_members(self) -> List[str]:
        return sorted(self._snaps)

    def fetch_delta(self, member: str, seq: int) -> Optional[bytes]:
        return self._deltas.get(member, {}).get(seq)

    def delta_seqs(self, member: str) -> List[int]:
        return sorted(self._deltas.get(member, {}))

    def delta_members(self) -> List[str]:
        return sorted(self._deltas)

    def close(self) -> None:
        pass
