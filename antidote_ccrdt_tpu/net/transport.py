"""Blob-plane `Transport` protocol + filesystem transport + state facade.

Extraction of the medium out of `parallel.elastic.GossipStore`: the
gossip tier's needs reduce to publishing/fetching OPAQUE BYTES keyed by
(member, kind, seq) plus a liveness surface. Everything the engines care
about — checkpoint headers, treedef validation, delta chaining — lives
ABOVE the medium in `GossipNode`, so `DeltaPublisher`, `sweep_deltas`,
`sweep`, and `my_replicas` run unchanged over a shared directory
(`FsTransport`), real sockets (`net.tcp.TcpTransport`), or the
deterministic simulator (`net.sim.SimTransport`).

Blob formats are transport-invariant:

* snapshot blob = ``u64le step ++ core.serial.dumps_dense(name, state)``
  (identical bytes to `harness.checkpoint.save_dense_checkpoint`, so
  on-disk artifacts from older rounds remain readable);
* delta blob    = ``core.serial.dumps_dense(f"{name}_delta", delta)``.

Heartbeats: `FsTransport` writes an 8-byte little-endian wall-clock
timestamp PAYLOAD into `hb-<member>` (atomic replace) and reads that —
file mtime is only the fallback for empty/foreign heartbeat files,
because mtime is flaky on coarse-granularity or object-store-backed
filesystems (the round-5 GossipStore relied on mtime alone). Socket and
sim transports track liveness via `net.membership` instead.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Any, List, Optional, Protocol, Tuple, runtime_checkable

from ..obs import events as obs_events
from ..obs import spans as obs_spans
from ..topo import zones as topo_zones
from ..utils import faults
from ..utils.metrics import Metrics

# -- range-framed delta blobs (ingest fast path) ------------------------------
# A compacted frame covers the publisher's windows [lo..hi] in ONE blob,
# published at seq=hi:  b"CCRF" ++ u64le lo ++ u64le hi ++ delta payload.
# The magic differs from core.serial's b"CCRD" at byte 3, so a legacy
# receiver handed a framed blob fails serial decode (total-failure policy
# reads None) and falls back to the snapshot anchor — backward interop
# without a wire-protocol version bump. A range-aware receiver strips the
# header and treats a bare payload as the degenerate frame [seq..seq].

FRAME_MAGIC = b"CCRF"
_FRAME_HDR = len(FRAME_MAGIC) + 16  # magic + u64 lo + u64 hi


def encode_range_frame(lo: int, hi: int, payload: bytes) -> bytes:
    """Wrap a serialized delta covering windows [lo..hi]."""
    if not 0 <= lo <= hi:
        raise ValueError(f"bad frame range [{lo}..{hi}]")
    return FRAME_MAGIC + struct.pack("<QQ", lo, hi) + payload


def decode_range_frame(
    blob: bytes, seq: int
) -> Tuple[int, int, bytes]:
    """(lo, hi, payload) of a delta blob fetched at `seq`: framed blobs
    decode their header, bare (legacy) blobs read as [seq..seq]."""
    if blob[:4] == FRAME_MAGIC and len(blob) >= _FRAME_HDR:
        lo, hi = struct.unpack_from("<QQ", blob, 4)
        return int(lo), int(hi), blob[_FRAME_HDR:]
    return seq, seq, blob


def frame_range(blob: bytes, seq: int) -> Tuple[int, int]:
    """Header-only peek at the windows a delta blob covers."""
    lo, hi, _ = decode_range_frame(blob[:_FRAME_HDR], seq)
    return lo, hi


@runtime_checkable
class Transport(Protocol):
    """What a gossip medium must provide. Blobs are opaque bytes; `seq`
    namespacing and retention (`keep`) follow the delta-shipping
    discipline documented in `parallel.delta`. All methods must be
    total: a missing/torn/unreachable artifact reads as None/[], never
    an exception — join-based gossip retries on the next sweep."""

    member: str

    # -- liveness ----------------------------------------------------------
    def heartbeat(self) -> None: ...
    def members(self) -> List[str]: ...
    def alive_members(self, timeout_s: float) -> List[str]: ...

    # -- snapshots (latest-wins, one slot per member) ----------------------
    def publish(self, blob: bytes) -> None: ...
    def fetch(self, member: str) -> Optional[bytes]: ...
    def fetch_head(self, member: str, n: int) -> Optional[bytes]: ...
    def snapshot_members(self) -> List[str]: ...

    # -- deltas (per-member seq-keyed window) ------------------------------
    def publish_delta(self, seq: int, blob: bytes, keep: int = 16) -> None: ...
    def fetch_delta(self, member: str, seq: int) -> Optional[bytes]: ...
    def delta_seqs(self, member: str) -> List[int]: ...
    def delta_members(self) -> List[str]: ...

    # -- partition plane (optional; see core.partition) --------------------
    # Digest blobs are tiny P+1-entry summaries pushed like snapshots;
    # psnap blobs are per-partition partial snapshots that are STORED,
    # not broadcast — peers pull only divergent partitions. Transports
    # without these methods degrade to whole-instance resync (GossipNode
    # probes with getattr), which is also the mixed-version-fleet path.
    def publish_digest(self, blob: bytes) -> None: ...
    def fetch_digest(self, member: str) -> Optional[bytes]: ...
    def publish_psnap(self, part: int, blob: bytes) -> None: ...
    def fetch_psnap(self, member: str, part: int) -> Optional[bytes]: ...
    def request_psnaps(self, member: str, parts: List[int]) -> None: ...

    def close(self) -> None: ...

    def peers(self) -> List[str]:
        """Everyone ever seen, excluding self."""
        ...


class FsTransport:
    """Shared-directory medium (the round-5 `GossipStore` file layout).

    Layout: `<root>/snap-<member>` (latest snapshot blob, atomic
    replace), `<root>/delta-<member>-<seq:08d>`, `<root>/hb-<member>`
    (8-byte timestamp payload, mtime fallback). One writer per member
    id; any number of readers."""

    def __init__(self, root: str, member: str, metrics: Optional[Metrics] = None):
        self.root = root
        self.member = member
        self.metrics = metrics if metrics is not None else Metrics()
        os.makedirs(root, exist_ok=True)
        self.heartbeat()

    # -- liveness ----------------------------------------------------------

    def heartbeat(self) -> None:
        p = os.path.join(self.root, f"hb-{self.member}")
        # Thread-unique tmp name: with the overlap pipeline, the
        # heartbeat daemon and the host-stage thread (publish →
        # heartbeat) beat concurrently — a shared tmp would let one
        # thread's replace() delete the other's file mid-write.
        tmp = f"{p}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(struct.pack("<d", time.time()))
        os.replace(tmp, p)

    def _heartbeat_age(self, member: str) -> Optional[float]:
        """Seconds since `member` last beat, or None (no evidence).
        Reads the timestamp payload; falls back to file mtime for
        empty/short files (a foreign writer using the pre-payload
        format, or a torn write)."""
        p = os.path.join(self.root, f"hb-{member}")
        try:
            with open(p, "rb") as f:
                payload = f.read(8)
            if len(payload) == 8:
                return time.time() - struct.unpack("<d", payload)[0]
            return time.time() - os.path.getmtime(p)
        except OSError:
            return None

    def members(self) -> List[str]:
        return sorted(
            f[3:]
            for f in os.listdir(self.root)
            if f.startswith("hb-") and ".tmp" not in f
        )

    def peers(self) -> List[str]:
        return [m for m in self.members() if m != self.member]

    def alive_members(self, timeout_s: float) -> List[str]:
        """Members whose heartbeat is fresher than `timeout_s`. Always
        includes self (a member never suspects itself)."""
        out = []
        for m in self.members():
            if m == self.member:
                out.append(m)
                continue
            age = self._heartbeat_age(m)
            if age is not None and age <= timeout_s:
                out.append(m)
        return sorted(out)

    # -- snapshots ---------------------------------------------------------

    def publish(self, blob: bytes) -> None:
        if faults.ACTIVE:
            mangled = faults.mangle("transport.publish", blob)
            if mangled is None:
                return  # injected drop: the publish silently never lands
            blob = mangled
        path = os.path.join(self.root, f"snap-{self.member}")
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.heartbeat()

    def fetch(self, member: str) -> Optional[bytes]:
        try:
            with open(os.path.join(self.root, f"snap-{member}"), "rb") as f:
                return f.read()
        except OSError:
            return None

    def fetch_head(self, member: str, n: int) -> Optional[bytes]:
        try:
            with open(os.path.join(self.root, f"snap-{member}"), "rb") as f:
                return f.read(n)
        except OSError:
            return None

    def snapshot_members(self) -> List[str]:
        return sorted(
            f[5:]
            for f in os.listdir(self.root)
            if f.startswith("snap-") and ".tmp" not in f
        )

    # -- deltas ------------------------------------------------------------

    def publish_delta(self, seq: int, blob: bytes, keep: int = 16) -> None:
        if faults.ACTIVE:
            mangled = faults.mangle("transport.publish_delta", blob)
            if mangled is None:
                return  # injected drop
            blob = mangled
        path = os.path.join(self.root, f"delta-{self.member}-{seq:08d}")
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            # fsync BEFORE the rename commits the name, matching `publish`:
            # without it a crash can leave delta-<m>-<seq> present but
            # empty/torn, which a peer reads as seq-present-but-garbage
            # (fetch_delta decodes to None forever — a permanent chain
            # break at that seq until the window prunes it).
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # The fs-medium analog of a tcp frame.send: the moment this
        # origin's delta became visible to peers. Same (origin, dseq)
        # trace context, so delta_paths() sees one "write"/"send" stage
        # regardless of medium.
        obs_events.emit(
            "transport.delta_write",
            origin=self.member,
            dseq=seq,
            bytes=len(blob),
        )
        self.heartbeat()
        for s in self.delta_seqs(self.member):
            if s <= seq - keep:
                try:
                    os.remove(
                        os.path.join(self.root, f"delta-{self.member}-{s:08d}")
                    )
                except OSError:
                    pass

    def fetch_delta(self, member: str, seq: int) -> Optional[bytes]:
        try:
            # The fault point sits INSIDE the try: an injected OSError
            # reads as None, preserving the Transport totality contract
            # (exactly how a real EIO on this read must behave).
            if faults.ACTIVE:
                faults.fire("transport.fetch_delta")
            with open(
                os.path.join(self.root, f"delta-{member}-{seq:08d}"), "rb"
            ) as f:
                blob = f.read()
            if faults.ACTIVE:
                blob = faults.mangle("transport.fetch_delta.read", blob)
            return blob
        except OSError:
            return None

    def delta_seqs(self, member: str) -> List[int]:
        pre = f"delta-{member}-"
        out = []
        for f in os.listdir(self.root):
            if f.startswith(pre) and ".tmp" not in f:
                try:
                    out.append(int(f[len(pre):]))
                except ValueError:
                    continue
        return sorted(out)

    def delta_members(self) -> List[str]:
        # Strip "delta-" prefix and "-<seq>" suffix (member names may
        # themselves contain dashes).
        return sorted(
            {
                f[len("delta-"):].rsplit("-", 1)[0]
                for f in os.listdir(self.root)
                if f.startswith("delta-") and ".tmp" not in f
            }
        )

    # -- partition plane ---------------------------------------------------
    # `dig-<member>` (latest digest vector blob, atomic replace) and
    # `psnap-<member>-<part:04d>`. On a shared directory the fetch IS the
    # request, so `request_psnaps` is a no-op and partial resync resolves
    # within one sweep.

    def publish_digest(self, blob: bytes) -> None:
        if faults.ACTIVE:
            mangled = faults.mangle("transport.publish", blob)
            if mangled is None:
                return
            blob = mangled
        path = os.path.join(self.root, f"dig-{self.member}")
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def fetch_digest(self, member: str) -> Optional[bytes]:
        try:
            with open(os.path.join(self.root, f"dig-{member}"), "rb") as f:
                return f.read()
        except OSError:
            return None

    def publish_psnap(self, part: int, blob: bytes) -> None:
        if faults.ACTIVE:
            mangled = faults.mangle("transport.publish", blob)
            if mangled is None:
                return
            blob = mangled
        path = os.path.join(self.root, f"psnap-{self.member}-{part:04d}")
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def fetch_psnap(self, member: str, part: int) -> Optional[bytes]:
        try:
            with open(
                os.path.join(self.root, f"psnap-{member}-{part:04d}"), "rb"
            ) as f:
                return f.read()
        except OSError:
            return None

    def request_psnaps(self, member: str, parts: List[int]) -> None:
        pass  # pull medium: fetch_psnap reads the peer's files directly

    def close(self) -> None:
        pass


class GossipNode:
    """State-plane facade over any `Transport`: the exact surface the
    round-5 `GossipStore` exposed to `parallel.elastic`, so every gossip
    entry point (`DeltaPublisher`, `sweep`, `sweep_deltas`,
    `my_replicas`) and drill runs unchanged over filesystem, TCP, or
    simulated media.

    Encoding/decoding and validation live here (not in transports):
    snapshot blobs carry the dense-checkpoint layout, fetches are TOTAL
    (any decode/validation failure reads as None — a torn concurrent
    write or a peer on a mismatched engine config must be skipped, not
    crash the gossip loop; the next sweep retries)."""

    def __init__(self, transport: Transport, metrics: Optional[Metrics] = None):
        self.transport = transport
        self.member = transport.member
        # Zone passthrough for transports running the topo/ layer, with
        # the CCRDT_ZONE env fallback for zone-less media (FsTransport):
        # a mesh-sharded worker gossiping through a shared directory is
        # still ON a slice (topo/zones.py slice_zone), and drills and
        # dashboards read the label off the node instead of reaching
        # into the transport. None when neither source names one.
        self.zone = (
            getattr(transport, "zone", None)
            or os.environ.get(topo_zones.ENV_ZONE)
            or None
        )
        self.metrics = (
            metrics
            if metrics is not None
            else getattr(transport, "metrics", None) or Metrics()
        )

    # -- liveness (delegated) ----------------------------------------------

    def heartbeat(self) -> None:
        self.transport.heartbeat()

    def members(self) -> List[str]:
        return self.transport.members()

    def alive_members(self, timeout_s: float) -> List[str]:
        return self.transport.alive_members(timeout_s)

    # -- snapshots ---------------------------------------------------------

    def publish(self, name: str, state: Any, step: int) -> None:
        """Atomically publish this member's state at `step` (and beat)."""
        from ..core import serial

        blob = struct.pack("<Q", step) + serial.dumps_dense(name, state)
        self.metrics.count("net.snap_publishes")
        self.metrics.count("net.snap_bytes", len(blob))
        obs_events.emit(
            "snap.publish", origin=self.member, step=step, bytes=len(blob)
        )
        if obs_spans.ACTIVE:
            # Handing the blob to the medium (fs write / tcp enqueue /
            # sim heap push) — the host cost of putting it in flight.
            with obs_spans.span(
                "round.gossip_send", kind="snap", step=step, bytes=len(blob)
            ):
                self.transport.publish(blob)
        else:
            self.transport.publish(blob)

    def fetch(
        self, member: str, like: Any, dense: Any = None
    ) -> Optional[Tuple[int, Any]]:
        """Latest (step, state) published by `member`, or None. ANY decode
        or validation failure reads as None — see class docstring."""
        from ..core import serial

        tok = (
            obs_spans.begin("round.gossip_recv", kind="snap", origin=member)
            if obs_spans.ACTIVE
            else None
        )
        try:
            blob = self.transport.fetch(member)
        finally:
            obs_spans.end(tok)
        if blob is None:
            return None
        # Decode + validation are their own phase (round.delta_decode):
        # gossip_recv is the medium cost, this is the host parse cost —
        # splitting them is what lets the ingest gate see which side
        # regressed.
        dtok = (
            obs_spans.begin("round.delta_decode", kind="snap", origin=member)
            if obs_spans.ACTIVE
            else None
        )
        try:
            try:
                (step,) = struct.unpack("<Q", blob[:8])
                _name, state = serial.loads_dense(blob[8:], like)
                if dense is not None:
                    from ..utils.validate import check_state

                    check_state(dense, state)
            except Exception:  # noqa: BLE001 — deliberately total, see docstring
                return None
            self.metrics.count("net.snap_fetches")
            return step, state
        finally:
            obs_spans.end(dtok)

    def snapshot_seq(self, member: str) -> Optional[int]:
        """Seq/step of `member`'s snapshot from its 8-byte header —
        without parsing the (large) payload."""
        hdr = self.transport.fetch_head(member, 8)
        if hdr is None or len(hdr) < 8:
            return None
        return struct.unpack("<Q", hdr)[0]

    def snapshot_members(self) -> List[str]:
        return self.transport.snapshot_members()

    # -- deltas ------------------------------------------------------------

    def publish_delta(
        self, delta_blob: bytes, seq: int, keep: int = 16,
        lo: Optional[int] = None,
    ) -> None:
        """Atomically publish a serialized delta at `seq`; retain only the
        last `keep` (receivers that fall off the window resync from the
        full snapshot). With `lo` < `seq` the blob ships range-framed: one
        compacted frame covering the publisher's windows [lo..seq] (the
        ingest fast path — see `encode_range_frame`)."""
        if lo is not None and lo < seq:
            delta_blob = encode_range_frame(lo, seq, delta_blob)
            self.metrics.count("ingest.coalesced_frames")
            self.metrics.count("ingest.coalesced_ops", seq - lo + 1)
        else:
            lo = seq
        self.metrics.count("net.delta_publishes")
        self.metrics.count("net.delta_bytes", len(delta_blob))
        # Stage 1 of the delta propagation path: this replica minted
        # (origin, dseq) — a compacted frame mints the whole [lo..dseq]
        # range at once, and the audit treats `lo` as its chain link.
        obs_events.emit(
            "delta.publish",
            origin=self.member,
            dseq=seq,
            lo=lo,
            bytes=len(delta_blob),
        )
        if obs_spans.ACTIVE:
            with obs_spans.span(
                "round.gossip_send", kind="delta", origin=self.member,
                dseq=seq, bytes=len(delta_blob),
            ):
                self.transport.publish_delta(seq, delta_blob, keep=keep)
        else:
            self.transport.publish_delta(seq, delta_blob, keep=keep)

    def fetch_delta_blob(
        self, member: str, seq: int
    ) -> Optional[Tuple[int, int, bytes]]:
        """Raw (lo, hi, payload) at `seq` — the fetch half of
        `fetch_delta_framed`, billed to `round.gossip_recv` only. The
        prefetcher's batched decode stage pulls blobs through this and
        decodes them in one `round.delta_decode` pass."""
        tok = (
            obs_spans.begin(
                "round.gossip_recv", kind="delta", origin=member, dseq=seq
            )
            if obs_spans.ACTIVE
            else None
        )
        try:
            blob = self.transport.fetch_delta(member, seq)
        finally:
            obs_spans.end(tok)
        if blob is None:
            return None
        try:
            return decode_range_frame(blob, seq)
        except Exception:  # noqa: BLE001 — torn header reads as missing
            return None

    def decode_delta_blob(
        self, member: str, seq: int, payload: bytes, like_delta: Any,
        validate=None,
    ) -> Optional[Any]:
        """Deserialize + validate one fetched delta payload, billed to
        `round.delta_decode`. Same total-failure policy as `fetch`."""
        from ..core import serial

        tok = (
            obs_spans.begin(
                "round.delta_decode", kind="delta", origin=member, dseq=seq
            )
            if obs_spans.ACTIVE
            else None
        )
        try:
            try:
                _name, delta = serial.loads_dense(payload, like_delta)
                if validate is not None and not validate(delta):
                    return None
            except Exception:  # noqa: BLE001 — see fetch
                return None
            self.metrics.count("net.delta_fetches")
            obs_events.emit("delta.fetch", origin=member, dseq=seq)
            return delta
        finally:
            obs_spans.end(tok)

    def fetch_delta_framed(
        self, member: str, seq: int, like_delta: Any, validate=None
    ) -> Optional[Tuple[int, int, Any]]:
        """(lo, hi, delta) of the (possibly range-framed) delta stored at
        `seq`; bare legacy blobs read as the degenerate frame
        [seq..seq]. None on any fetch/decode/validate failure."""
        got = self.fetch_delta_blob(member, seq)
        if got is None:
            return None
        lo, hi, payload = got
        delta = self.decode_delta_blob(
            member, seq, payload, like_delta, validate=validate
        )
        if delta is None:
            return None
        return lo, hi, delta

    def fetch_delta(
        self, member: str, seq: int, like_delta: Any, validate=None
    ) -> Optional[Any]:
        """Deserialized delta at `seq`, or None (missing/torn/pruned/
        mis-configured — same total-failure policy as `fetch`). `validate`
        (delta -> bool) rejects structurally-decodable deltas from a peer
        on a DIFFERENT engine config before expansion can index out of
        range downstream. Range-framed blobs decode to their inner delta
        (use `fetch_delta_framed` when the covered range matters)."""
        got = self.fetch_delta_framed(member, seq, like_delta, validate)
        return None if got is None else got[2]

    def delta_seqs(self, member: str) -> List[int]:
        return self.transport.delta_seqs(member)

    def delta_members(self) -> List[str]:
        return self.transport.delta_members()

    # -- partition plane ---------------------------------------------------
    # Degrades per-method via getattr: a transport without the partition
    # surface (or a legacy peer that never publishes digests) reads as
    # None everywhere, and callers fall back to whole-instance resync.

    def partitions_supported(self) -> bool:
        return all(
            hasattr(self.transport, m)
            for m in ("publish_digest", "fetch_digest", "publish_psnap",
                      "fetch_psnap", "request_psnaps")
        )

    def publish_partitioned(
        self, name: str, state: Any, seq: int, dense: Any, P: int,
        plan: Optional[Any] = None, pager: Optional[Any] = None,
    ) -> Optional[Any]:
        """Anchor-time partition publish: the P+1 digest vector (pushed
        like a snapshot — tiny) plus psnap blobs for every partition whose
        digest changed since the last anchor (ALL partitions on the first;
        the psnap store is cumulative, so it is complete from then on).
        With a `mesh.MeshPlan`, the digest vector and the psnaps are
        produced shard by shard — each key shard contributes exactly the
        slice it owns, stitched back into the same wire blobs (the
        artifacts are byte-identical either way, which test_mesh.py
        pins), billing per-shard counters for the chaos gate. With a
        `core.pager.PartitionPager`, digests and psnaps for demoted
        partitions come from the pager's stored CCPT blobs — the
        transfer format is the storage format, so a cold partition is
        served without hydrating. Returns the digest vector, or None
        when the medium has no partition surface."""
        from ..core import partition as pt
        from ..core import serial

        pub_dig = getattr(self.transport, "publish_digest", None)
        pub_ps = getattr(self.transport, "publish_psnap", None)
        if pub_dig is None or pub_ps is None:
            return None
        if plan is not None:
            from ..mesh import gossip as mesh_gossip

            vec = mesh_gossip.sharded_digest_vector(
                state, plan, metrics=self.metrics, pager=pager
            )
        elif pager is not None and pager.has_cold():
            vec = pager.digest_vector(state)
        else:
            vec = pt.state_digests(state, P)
        cache = getattr(self, "_last_digests", None)
        if cache is None:
            cache = self._last_digests = {}
        prev = cache.get(name)
        changed = (
            list(range(P + 1))
            if prev is None or len(prev) != len(vec)
            else pt.divergent_parts(prev, vec)
        )
        if plan is not None:
            from ..mesh import gossip as mesh_gossip

            for shard, _parts in mesh_gossip.group_parts_by_shard(
                plan, changed
            ):
                for part, blob in mesh_gossip.shard_psnap_blobs(
                    name, state, seq, dense, plan, shard, parts=changed,
                    pager=pager,
                ):
                    self.metrics.count("net.psnap_publishes")
                    self.metrics.count(f"mesh.shard{shard:02d}.psnap_publishes")
                    pub_ps(part, blob)
            changed = []
        for part in changed:
            if pager is not None:
                blob = pager.psnap_blob(state, seq, part)
            else:
                payload = serial.dumps_dense(
                    f"{name}_psnap", pt.restrict_psnap(dense, state, part, P)
                )
                blob = pt.encode_psnap_blob(seq, part, payload)
            self.metrics.count("net.psnap_publishes")
            pub_ps(part, blob)
        dig_blob = pt.encode_digest_blob(seq, vec)
        self.metrics.count("net.dig_publishes")
        self.metrics.count("net.dig_bytes", len(dig_blob))
        pub_dig(dig_blob)
        cache[name] = vec
        return vec

    def fetch_digests(self, member: str) -> Optional[Tuple[int, Any]]:
        """(seq, uint32[P+1]) of `member`'s latest digest vector, or None
        (legacy peer / torn blob / no partition surface) — total."""
        from ..core import partition as pt

        fd = getattr(self.transport, "fetch_digest", None)
        if fd is None:
            return None
        blob = fd(member)
        if blob is None:
            return None
        try:
            seq, vec = pt.decode_digest_blob(blob)
        except Exception:  # noqa: BLE001 — total, same policy as fetch
            return None
        # The audit watchdog rides these fetches (one observe_peer per
        # successful digest exchange) — count them so the chaos gate can
        # prove the watchdog's feed never silently goes dark.
        self.metrics.count("net.dig_fetches")
        return seq, vec

    def fetch_psnap(
        self, member: str, part: int, like_delta: Any, validate=None
    ) -> Optional[Tuple[int, Any]]:
        """(seq, decoded psnap payload) for one partition, or None —
        total. Bills `net.psnap_bytes` (the anti-entropy bytes the
        partition plane exists to shrink)."""
        from ..core import partition as pt
        from ..core import serial

        fp = getattr(self.transport, "fetch_psnap", None)
        if fp is None:
            return None
        blob = fp(member, part)
        if blob is None:
            return None
        try:
            seq, got_part, payload = pt.decode_psnap_blob(blob)
            if got_part != part:
                return None
            _name, delta = serial.loads_dense(payload, like_delta)
            if validate is not None and not validate(delta):
                return None
        except Exception:  # noqa: BLE001 — see fetch
            return None
        self.metrics.count("net.psnap_fetches")
        self.metrics.count("net.psnap_bytes", len(blob))
        obs_events.emit(
            "psnap.fetch", origin=member, part=part, bytes=len(blob)
        )
        return seq, delta

    def request_psnaps(self, member: str, parts: List[int]) -> None:
        rq = getattr(self.transport, "request_psnaps", None)
        if rq is not None and parts:
            rq(member, list(parts))

    def close(self) -> None:
        self.transport.close()
