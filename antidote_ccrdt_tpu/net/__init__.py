"""net/: pluggable gossip anti-entropy transports.

The failure-tolerant tier (`parallel.elastic`) exchanges lattice states
and join-decomposed deltas between members. Through round 5 the only
medium was a shared filesystem directory (`GossipStore`) — fine for
single-host drills, a non-starter for multi-DC traffic. This package
makes the medium pluggable behind a small blob-plane `Transport`
protocol:

* `net.transport`  — the `Transport` protocol, the filesystem
  implementation (`FsTransport`), and `GossipNode`, the state-plane
  facade every `parallel.elastic` entry point speaks.
* `net.tcp`        — a real TCP peer: `{packet,4}` ETF frames (the
  bridge's framing), per-peer connection cache with exponential backoff
  + jitter, bounded send queues with a drop-oldest-delta-keep-anchor
  policy.
* `net.membership` — SWIM-style liveness: heartbeats piggybacked on
  every frame, suspect -> confirm-dead timeouts, alive set feeding the
  deterministic `parallel.elastic.owners` assignment.
* `net.sim`        — a deterministic in-process simulator (seeded RNG,
  virtual clock; latency / loss / duplication / partitions / crashes)
  for replay-exact chaos tests.

Both peer transports (`TcpTransport`, `SimTransport`) can trade the
default full mesh for the DCN-aware zone topology in `topo/` via
`install_router()` — leaves gossip intra-zone, per-zone rendezvous
anchors relay across zones, frames compress per-link (see `topo/`).
"""

from .membership import Membership
from .sim import SimNet, SimTransport
from .tcp import TcpTransport
from .transport import FsTransport, GossipNode, Transport

__all__ = [
    "Transport",
    "FsTransport",
    "GossipNode",
    "Membership",
    "TcpTransport",
    "SimNet",
    "SimTransport",
]
